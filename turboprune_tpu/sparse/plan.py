"""One ExecutionPlan: the single producer of sparse-backend decisions.

Before this module the repo had three separately-wired execution paths —
masked-dense, channel compaction (compact.py/train_compact.py) and gathered
N:M (nm_execute.py) — each with its own enter/exit logic in the harness and
its own probe branch in serve/engine.py, each globally on or off per run.
The N:M frontier bench showed the winner is workload-dependent (scattered
masks favor gathering, dead channels favor compaction), so any
single-backend run leaves speed on the floor for the layers where the other
backend wins.

``plan_execution`` derives ONE ``ExecutionPlan`` from the live masks:

* channel compaction is attempted first (whole-model width slicing, gated
  on ``CompactionPlan.savings()`` clearing ``compact_min_savings``);
* N:M gathering is then planned over the SURVIVORS — the same
  compact-then-gather composition the harness used, but decided in one
  place — routing each hook-eligible layer whose live contraction rows
  clear ``nm_min_axis_savings``;
* everything else stays masked-dense.

The plan carries the model-ctor overrides (``width_overrides`` /
``nm_overrides``), hashable cache keys, and a stable ``plan_signature()``
whose leading element is the plan KIND ("masked" / "compact" / "nm" /
"mixed") — the vocabulary the exec-manifest enumerates and the AOT cache
keys on. Every per-layer decision (backend, reason, estimated or measured
gain) lands in ``plan.report["decisions"]`` so routing is auditable and a
silent fallback to dense is visible, never implicit.

Autotune (``autotune="cost"`` or ``"measure"``) re-checks each routed N:M
layer against the masked-dense floor — an analytic gather-overhead cost
model, or a per-layer jitted micro-benchmark on the host platform — and
demotes layers where gathering would not pay. Compaction is not per-layer
tunable (the slice geometry is a whole-model property), so autotune only
refines the N:M routing inside the committed widths.

Gradients remain exactly masked-dense through any mix: compaction slices
coordinates whose gradients are exactly zero under the mask (anchor
expansion restores them), and ``nm_matmul``'s custom VJP keeps dw a full
dense GEMM — composing the two changes which coordinates are *materialized*,
never the values the optimizer sees.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import numpy as np

from .compact import CompactionPlan, build_plan, compact_tree
from .graph import CompactionError, build_graph
from .nm import _matrix_view, eligible_layers
from .nm_execute import (
    MIN_AXIS_SAVINGS,
    NMExecPlan,
    _hook_key,
    build_nm_plan,
    nm_matmul,
)

# Executable-surface hook: the plan-signature kind for MIXED plans (both a
# compaction and an N:M component). analysis/exec_manifest.py enumerates
# every PLAN_SIGNATURE_KIND declaration in the package so the manifest and
# the AOT cache agree on the signature vocabulary; single-backend plans
# reuse the kinds declared by compact.py / nm_execute.py / serve/engine.py.
PLAN_SIGNATURE_KIND = "mixed"

# Planner enables. "force" commits compaction whenever the plan builds —
# even the identity slice — and lets CompactionError propagate: the
# explicit-backend serving contract ("compact means compact, and say so
# honestly in the report"). "auto" gates on the savings threshold and
# records failures as decisions instead of raising.
COMPACT_MODES = ("auto", "force", "off")
NM_MODES = ("auto", "off")
AUTOTUNE_MODES = ("off", "cost", "measure")

# Analytic gather overhead as a fraction of the dense layer cost: two
# static takes on the operands plus (transposable only) the output
# scatter. Calibrated loosely from the nm_frontier bench's small-layer
# floor; autotune="measure" replaces it with a real timing.
_GATHER_OVERHEAD = 0.15


@dataclasses.dataclass
class ExecutionPlan:
    """The one decision object every execution surface consumes.

    ``compaction``/``nm`` hold only COMMITTED backend plans (None = that
    backend does not run). ``decisions`` is the machine-readable routing
    table; ``report`` is the full audit record including both sub-reports.
    """

    compaction: Optional[CompactionPlan]
    nm: Optional[NMExecPlan]
    decisions: dict
    report: dict

    @property
    def kind(self) -> str:
        """Plan-signature kind: which backend(s) actually run."""
        if self.compaction is not None and self.nm is not None:
            return "mixed"
        if self.compaction is not None:
            return "compact"
        if self.nm is not None:
            return "nm"
        return "masked"

    @property
    def width_overrides(self) -> Optional[dict]:
        """Model-ctor width overrides, None when compaction does not run."""
        return self.compaction.width_overrides if self.compaction else None

    @property
    def nm_overrides(self) -> Optional[dict]:
        """Model-ctor N:M hook overrides, None when gathering does not run."""
        return self.nm.overrides if self.nm else None

    def width_key(self) -> tuple:
        """Hashable compaction component of step/eval cache keys."""
        return self.compaction.as_override_tuple() if self.compaction else ()

    def nm_key(self) -> tuple:
        """Hashable N:M component of step cache keys."""
        return self.nm.as_override_tuple() if self.nm else ()

    def plan_signature(self) -> tuple:
        """(kind, ...) executable-cache signature — the plan component of
        AOT keys (serve/fleet/aot_cache.py make_key). Single-backend plans
        emit exactly the signatures their modules emitted before the
        planner existed, so warm AOT caches stay warm across the refactor."""
        kind = self.kind
        if kind == "compact":
            return ("compact", self.width_key())
        if kind == "nm":
            return ("nm", self.nm_key())
        if kind == "mixed":
            return (PLAN_SIGNATURE_KIND, self.width_key(), self.nm_key())
        return ("masked",)


def _default_factory(model) -> Callable[..., Any]:
    """clone()-based model factory for callers that don't pass one."""

    def factory(width_overrides=None, nm_overrides=None):
        kw = {}
        if width_overrides:
            kw["width_overrides"] = tuple(sorted(dict(width_overrides).items()))
        if nm_overrides:
            kw["nm_overrides"] = tuple(sorted(dict(nm_overrides).items()))
        return model.clone(**kw) if kw else model

    return factory


def _plan_compaction(
    model, params, masks, batch_stats, mode: str, min_savings: float
) -> tuple[Optional[CompactionPlan], dict]:
    """Compaction stage: build the slice plan and decide commit/decline."""
    if mode == "off":
        return None, {
            "backend": "dense",
            "committed": False,
            "reason": "compaction disabled",
        }
    try:
        graph = build_graph(model, params)
        candidate = build_plan(params, masks, graph, batch_stats)
    except CompactionError as e:
        if mode == "force":
            raise
        return None, {
            "backend": "dense",
            "committed": False,
            "reason": f"CompactionError: {e}",
        }
    savings = candidate.savings()
    if mode == "force":
        commit, reason = True, "backend forced compact"
    elif savings <= 0.0:
        commit, reason = False, "no dead channels to slice"
    elif savings < min_savings:
        commit, reason = (
            False,
            f"savings {savings:.4f} below threshold {min_savings}",
        )
    else:
        commit, reason = (
            True,
            f"savings {savings:.4f} clears threshold {min_savings}",
        )
    decision = {
        "backend": "compact" if commit else "dense",
        "committed": commit,
        "savings": round(float(savings), 6),
        "params_before": candidate.report["params_before"],
        "params_after": candidate.report["params_after"],
        "channels_before": candidate.report["channels_before"],
        "channels_after": candidate.report["channels_after"],
        "reason": reason,
    }
    return (candidate if commit else None), decision


def _time_call(fn, *args) -> float:
    """Best-of-3 wall ms for an already-warm jitted call."""
    import jax

    jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, (time.perf_counter() - t0) * 1e3)
    return best


def _nm_layer_estimates(
    nplan: NMExecPlan, shapes: dict, mode: str
) -> dict[str, dict]:
    """Per routed hook-key: estimated (cost model) or measured (micro-bench)
    nm-vs-dense gain. Gain < 1.0 means gathering would LOSE to masked-dense
    for that layer and autotune demotes it."""
    import jax
    import jax.numpy as jnp

    out: dict[str, dict] = {}
    for key, (ki, ko) in nplan.overrides.items():
        i, o = shapes[key]
        if mode == "cost":
            kept_in = len(ki) / i
            kept_out = (len(ko) / o) if ko is not None else 1.0
            est_cost = kept_in * kept_out + _GATHER_OVERHEAD
            out[key] = {
                "mode": "cost",
                "est_gain": round(1.0 / est_cost, 4),
            }
            continue
        # measure: time the two executables on a synthetic batch. Runs on
        # whatever platform the caller is pinned to (the bench and the
        # harness both plan on CPU); index maps are compile-time metadata.
        x = jnp.ones((32, i), jnp.float32)
        w = jnp.ones((i, o), jnp.float32)
        b = jnp.zeros((o,), jnp.float32)
        # graftlint: disable=retrace-hazard -- one jit per routed layer by design: each (ki, ko) index map is a distinct executable; both are timed once and discarded
        dense_fn = jax.jit(lambda x2, w2, b2: x2 @ w2 + b2)
        # graftlint: disable=retrace-hazard -- one jit per routed layer by design: nm_matmul's index tuples are static argnums, so each layer is necessarily its own program
        nm_fn = jax.jit(lambda x2, w2, b2: nm_matmul(ki, ko, x2, w2, b2))
        dense_ms = _time_call(dense_fn, x, w, b)
        nm_ms = _time_call(nm_fn, x, w, b)
        out[key] = {
            "mode": "measure",
            "dense_ms": round(dense_ms, 5),
            "nm_ms": round(nm_ms, 5),
            "est_gain": round(dense_ms / max(nm_ms, 1e-9), 4),
        }
    return out


def _demote(nplan: NMExecPlan, drop: set, key_by_name: dict) -> NMExecPlan:
    """Rebuild the N:M plan without the demoted hook keys, keeping the
    report's coverage accounting honest."""
    overrides = {k: v for k, v in nplan.overrides.items() if k not in drop}
    layers = {}
    routed_params = 0
    for name, info in nplan.report["layers"].items():
        info = dict(info)
        if info["routed"] and key_by_name.get(name) in drop:
            info["routed"] = False
        if info["routed"]:
            routed_params += info["numel"]
        layers[name] = info
    eligible = nplan.report["eligible_params"]
    report = {
        "eligible_params": eligible,
        "routed_params": routed_params,
        "coverage_frac": routed_params / eligible if eligible else 0.0,
        "layers": layers,
    }
    return NMExecPlan(overrides=overrides, report=report)


def plan_execution(
    model,
    params,
    masks,
    batch_stats=None,
    *,
    model_factory: Optional[Callable[..., Any]] = None,
    compact: str = "auto",
    nm: str = "auto",
    compact_min_savings: float = 0.0,
    nm_min_axis_savings: float = MIN_AXIS_SAVINGS,
    autotune: str = "off",
) -> ExecutionPlan:
    """Derive this level's ExecutionPlan from the live masks.

    Pure function of replicated inputs — every host derives the identical
    plan, so no collective is needed to agree on it (callers that gate
    jittable work on the outcome, like compact-as-you-train, still barrier
    on the derived signature; see the harness).

    ``compact``: "auto" (commit when ``savings()`` > 0 and clears
    ``compact_min_savings``), "force" (commit whenever the plan builds,
    CompactionError propagates — explicit-backend serving semantics), or
    "off". ``nm``: "auto" or "off". ``autotune`` refines the N:M routing
    against the masked-dense floor: "cost" (analytic) or "measure"
    (per-layer jitted micro-benchmark).
    """
    if compact not in COMPACT_MODES:
        raise ValueError(f"compact mode {compact!r} not in {COMPACT_MODES}")
    if nm not in NM_MODES:
        raise ValueError(f"nm mode {nm!r} not in {NM_MODES}")
    if autotune not in AUTOTUNE_MODES:
        raise ValueError(f"autotune {autotune!r} not in {AUTOTUNE_MODES}")
    batch_stats = batch_stats or {}
    factory = model_factory or _default_factory(model)

    cplan, comp_decision = _plan_compaction(
        model, params, masks, batch_stats, compact, compact_min_savings
    )

    nplan: Optional[NMExecPlan] = None
    nm_report: Optional[dict] = None
    layer_decisions: dict[str, dict] = {}
    if nm != "off":
        # Compose over the committed widths: gather the SURVIVORS. The
        # sliced masks stay exact because routing keys on live rows/cols,
        # not block alignment (see build_nm_plan).
        if cplan is not None and cplan.width_overrides:
            exec_model = factory(width_overrides=cplan.width_overrides)
            live_masks = compact_tree(masks, cplan)
        else:
            exec_model = model
            live_masks = masks
        candidate = build_nm_plan(
            exec_model, live_masks, min_axis_savings=nm_min_axis_savings
        )
        nm_report = candidate.report
        key_by_name = {}
        shapes = {}
        for name, shape, s in eligible_layers(live_masks):
            key = _hook_key(exec_model, name, shape)
            key_by_name[name] = key
            if key is not None:
                shapes[key] = _matrix_view(shape, s)
        estimates: dict[str, dict] = {}
        if candidate.overrides and autotune != "off":
            estimates = _nm_layer_estimates(candidate, shapes, autotune)
            drop = {k for k, e in estimates.items() if e["est_gain"] < 1.0}
            if drop:
                candidate = _demote(candidate, drop, key_by_name)
            nm_report = candidate.report
        if candidate.overrides:
            nplan = candidate
        for name, info in nm_report["layers"].items():
            key = key_by_name.get(name)
            if info["routed"]:
                decision = {
                    "backend": "nm",
                    "reason": (
                        f"live rows {info['kept_in_frac']:.3f} clear "
                        f"axis-savings threshold {nm_min_axis_savings}"
                    ),
                }
            elif not info["hookable"]:
                decision = {
                    "backend": "dense",
                    "reason": "no gathered-execution hook for this layer",
                }
            elif key in estimates and estimates[key]["est_gain"] < 1.0:
                decision = {
                    "backend": "dense",
                    "reason": "autotune: gather overhead beats the "
                    "reduced-GEMM win for this layer",
                }
            else:
                decision = {
                    "backend": "dense",
                    "reason": (
                        f"live rows {info['kept_in_frac']:.3f} above "
                        f"axis-savings threshold {nm_min_axis_savings}"
                    ),
                }
            if key in estimates:
                decision.update(estimates[key])
            layer_decisions[name] = decision

    decisions = {"compaction": comp_decision, "layers": layer_decisions}
    plan = ExecutionPlan(
        compaction=cplan, nm=nplan, decisions=decisions, report={}
    )
    routed = len(nplan.overrides) if nplan is not None else 0
    dense_layers = sum(
        1 for d in layer_decisions.values() if d["backend"] == "dense"
    )
    plan.report = {
        "kind": plan.kind,
        "autotune": autotune,
        "backend_counts": {
            "nm_layers": routed,
            "dense_layers": dense_layers,
            "compact_spaces": (
                cplan.report.get("compacted_spaces", 0) if cplan else 0
            ),
        },
        "coverage_frac": nm_report["coverage_frac"] if nm_report else 0.0,
        "compaction": comp_decision,
        "nm": nm_report,
        "decisions": decisions,
    }
    return plan
