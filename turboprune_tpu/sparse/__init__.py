"""Make sparsity pay: dead-channel compaction for eval/serving.

graph.py    mask-structure analysis — channel spaces with per-architecture
            propagation (VGG chains, ResNet stops at residual joins,
            DenseNet concat-aware offsets, ViT MLP blocks)
compact.py  ``compact_params`` — physically slice dead channels out of
            params/bias/BN leaves, returning smaller dense tensors + the
            ``width_overrides`` needed to re-instantiate the model, with a
            numeric-residue guard that keeps any dead channel whose
            relu(bn(0)) constant is nonzero (exactness over size)

Consumed by serve/engine.py (``compact: true`` load path), the harness's
opt-in compacted eval, and bench.py's ``compaction`` stage.
"""

from .compact import CompactionResult, analyze_masks, compact_params
from .graph import CompactionError, PropagationGraph, build_graph

__all__ = [
    "CompactionError",
    "CompactionResult",
    "PropagationGraph",
    "analyze_masks",
    "build_graph",
    "compact_params",
]
