"""Make sparsity pay: dead-channel compaction for train, eval and serving.

graph.py          mask-structure analysis — channel spaces with
                  per-architecture propagation (VGG chains, ResNet stops at
                  residual joins, DenseNet concat-aware offsets, ViT MLP
                  blocks)
compact.py        ``build_plan`` (keep vectors + shape report) and the
                  generic ``compact_tree``/``expand_tree`` slice/scatter
                  pair; ``compact_params`` — mask-folded smaller tensors +
                  ``width_overrides`` for eval/serving, with a
                  numeric-residue guard that keeps any dead channel whose
                  relu(bn(0)) constant is nonzero (exactness over size)
train_compact.py  ``compact_train_state``/``expand_train_state`` — the
                  WHOLE TrainState (raw params, masks, BN stats, optax
                  moments) sliced for compact-as-you-train and scattered
                  back to full coordinates for pruning/rewind/checkpoints

Consumed by serve/engine.py (``compact: true`` load path), the harness's
compact eval AND compact train paths, and bench.py's ``compaction`` /
``compact_train`` stages.
"""

from .compact import (
    CompactionPlan,
    CompactionResult,
    analyze_masks,
    build_plan,
    compact_params,
    compact_stats,
    compact_tree,
    expand_stats,
    expand_tree,
)
from .graph import CompactionError, PropagationGraph, build_graph
from .train_compact import (
    compact_train_state,
    expand_opt_state,
    expand_train_state,
    slice_opt_state,
    width_signature,
)

__all__ = [
    "CompactionError",
    "CompactionPlan",
    "CompactionResult",
    "PropagationGraph",
    "analyze_masks",
    "build_graph",
    "build_plan",
    "compact_params",
    "compact_stats",
    "compact_tree",
    "compact_train_state",
    "expand_opt_state",
    "expand_stats",
    "expand_train_state",
    "expand_tree",
    "slice_opt_state",
    "width_signature",
]
