"""Make sparsity pay: dead-channel compaction for train, eval and serving.

graph.py          mask-structure analysis — channel spaces with
                  per-architecture propagation (VGG chains, ResNet stops at
                  residual joins, DenseNet concat-aware offsets, ViT MLP
                  blocks)
compact.py        ``build_plan`` (keep vectors + shape report) and the
                  generic ``compact_tree``/``expand_tree`` slice/scatter
                  pair; ``compact_params`` — mask-folded smaller tensors +
                  ``width_overrides`` for eval/serving, with a
                  numeric-residue guard that keeps any dead channel whose
                  relu(bn(0)) constant is nonzero (exactness over size)
train_compact.py  ``compact_train_state``/``expand_train_state`` — the
                  WHOLE TrainState (raw params, masks, BN stats, optax
                  moments) sliced for compact-as-you-train and scattered
                  back to full coordinates for pruning/rewind/checkpoints

nm.py             N:M projection — snap unstructured masks to separable
                  (transposable) N:M block patterns, highest preserved
                  magnitude per M-block, vmap-batched solvers
nm_execute.py     gathered N:M execution — static int32 index maps +
                  custom-VJP reduced-width matmul, NM* drop-in modules and
                  ``build_nm_plan``; the second execution backend next to
                  compaction (composable: compact first, N:M the survivors)
plan.py           ``plan_execution`` — the ONE planner that turns live masks
                  into an ``ExecutionPlan`` (compact the dead channels, N:M
                  the scattered survivors, dense where neither pays, with an
                  optional cost-model/micro-bench autotune pass) consumed by
                  the harness, the serving engine, and the bench alike

Consumed by serve/engine.py (planner-driven backend selection), the
harness's compact eval and plan-execution paths, and bench.py's
``compaction`` / ``compact_train`` / ``nm_frontier`` / ``mixed_plan``
stages.
"""

from .compact import (
    CompactionPlan,
    CompactionResult,
    analyze_masks,
    build_plan,
    compact_params,
    compact_stats,
    compact_tree,
    expand_stats,
    expand_tree,
)
from .graph import CompactionError, PropagationGraph, build_graph
from .nm import (
    NMError,
    check_divisibility,
    nm_pattern_inaxis,
    nm_pattern_transposable,
    project_masks,
)
from .nm_execute import NMExecPlan, build_nm_plan
from .plan import (
    AUTOTUNE_MODES,
    COMPACT_MODES,
    NM_MODES,
    ExecutionPlan,
    plan_execution,
)
from .train_compact import (
    compact_train_state,
    expand_opt_state,
    expand_train_state,
    slice_opt_state,
    width_signature,
)

__all__ = [
    "AUTOTUNE_MODES",
    "COMPACT_MODES",
    "CompactionError",
    "CompactionPlan",
    "CompactionResult",
    "ExecutionPlan",
    "NMError",
    "NMExecPlan",
    "NM_MODES",
    "PropagationGraph",
    "analyze_masks",
    "build_graph",
    "build_nm_plan",
    "build_plan",
    "check_divisibility",
    "compact_params",
    "compact_stats",
    "compact_tree",
    "compact_train_state",
    "expand_opt_state",
    "expand_stats",
    "expand_train_state",
    "expand_tree",
    "nm_pattern_inaxis",
    "nm_pattern_transposable",
    "plan_execution",
    "project_masks",
    "slice_opt_state",
    "width_signature",
]
