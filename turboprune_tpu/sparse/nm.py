"""N:M projection — snap unstructured masks to hardware-friendly patterns.

The second half of the sparsity-format axis (ROADMAP item 2): channel
compaction (compact.py) only cashes in when whole channels die; this module
converts the SCATTERED masks magnitude/ER-ERK pruning actually produces
into N:M block patterns the gathered execution path (nm_execute.py) can run
at reduced width.

Pattern semantics — separable N:M, shared across the non-contracted axis:
a layer's kernel is viewed as a 2D matrix W[I, O] (I = contraction width).
The projected pattern is ``keep_in ⊗ keep_out`` where ``keep_in`` keeps
exactly N of every M consecutive rows and (transposable variant only)
``keep_out`` keeps exactly N of every M consecutive columns. Every output
column then satisfies N:M along the contraction axis AND — transposable —
every input row satisfies N:M along the output axis, so the backward
``dx = dy @ Wᵀ`` contraction is reduced exactly like the forward
("Accelerated Sparse Neural Training", PAPERS.md). Because the pattern is
shared across the non-contracted axis, ONE static int32 index map gathers
the kept weights into dense ``[.., K·N/M]`` tensors — a true reduced-width
GEMM in pure XLA, which per-column element patterns cannot give.

Projection is monotone (``new_mask = old_mask ∧ pattern``): pruned weights
never resurrect, so the IMP ladder's global-threshold invariant (scores at
pruned positions are exactly 0) survives.

Solvers, both batched over blocks with ``vmap``:
  - greedy (baseline): per-block top-N of row magnitude sums — exact for a
    single axis.
  - transposable (TSENOR-style): alternating maximization over
    (keep_in, keep_out); each half-step is an exact per-block top-N given
    the other axis, so the preserved magnitude is monotonically
    non-decreasing from the greedy-both-axes initialization — the final
    pattern provably preserves >= the greedy baseline (property-tested).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.masking import PyTree, mask_leaves_with_path, path_name


class NMError(ValueError):
    """A layer's geometry cannot take the requested N:M pattern."""


# ---------------------------------------------------------------- geometry


def split_index(name: str, shape: tuple) -> Optional[int]:
    """Where the contraction axes of a kernel end: the 2D matmul view is
    ``(I, O) = (prod(shape[:s]), prod(shape[s:]))``. None = ineligible.

    - Dense kernels (I, O): s=1.
    - ViT qkv DenseGeneral kernels (D, H, hd): contraction D, s=1.
    - ViT out-projection kernel (H, hd, D): contraction (H, hd), s=2.
    - 1x1 conv kernels (1, 1, C, O): contraction C, s=3.
    - Anything else (spatial convs, embeddings) has no matmul view.
    """
    if len(shape) == 2:
        return 1
    if len(shape) == 3:
        # The only 3D kernels in the model zoo are flax-MHA-layout attention
        # projections; ``out`` contracts its two leading (head) axes.
        return 2 if name.endswith("out/kernel") else 1
    if len(shape) == 4 and shape[0] == 1 and shape[1] == 1:
        return 3
    return None


def _matrix_view(shape: tuple, s: int) -> tuple[int, int]:
    i = 1
    for d in shape[:s]:
        i *= int(d)
    o = 1
    for d in shape[s:]:
        o *= int(d)
    return i, o


def eligible_layers(masks: PyTree) -> list[tuple[str, tuple, int]]:
    """[(path_name, shape, split)] for every mask leaf with a matmul view."""
    out = []
    for path, m in mask_leaves_with_path(masks):
        name = path_name(path)
        s = split_index(name, tuple(m.shape))
        if s is not None:
            out.append((name, tuple(m.shape), s))
    return out


def check_divisibility(masks: PyTree, m_block: int) -> None:
    """Fail fast (harness init) when an eligible layer's CONTRACTION width
    does not divide into M-blocks — a clear error beats a mid-run crash at
    the first prune step. Non-divisible OUTPUT widths (e.g. 10-class heads)
    are fine: the projection degrades to input-axis-only there."""
    for name, shape, s in eligible_layers(masks):
        i, _ = _matrix_view(shape, s)
        if i % m_block:
            raise NMError(
                f"layer {name!r}: contraction width {i} (kernel shape "
                f"{shape}) is not divisible by M={m_block} — this layer "
                f"cannot take an N:{m_block} pattern"
            )


# ----------------------------------------------------------------- solvers


def _topn_per_block(scores: jax.Array, n: int, m: int) -> jax.Array:
    """Exact per-block top-N: bool keep vector with exactly n True per m
    consecutive entries. Batched over blocks with vmap; lax.top_k breaks
    ties by first index, so the result is deterministic."""
    blocks = scores.reshape(-1, m)
    idx = jax.vmap(lambda row: jax.lax.top_k(row, n)[1])(blocks)
    keep = jax.vmap(
        lambda row_idx: jnp.zeros((m,), jnp.bool_).at[row_idx].set(True)
    )(idx)
    return keep.reshape(-1)


def nm_pattern_inaxis(scores: jax.Array, n: int, m: int) -> jax.Array:
    """Greedy baseline: keep the N highest-magnitude rows of each M-block,
    scored by total magnitude across the output axis (exact for one axis).
    Returns keep_in, bool (I,)."""
    return _topn_per_block(scores.sum(axis=1), n, m)


def nm_pattern_transposable(
    scores: jax.Array, n: int, m: int, iters: int = 8
) -> tuple[jax.Array, jax.Array]:
    """TSENOR-style transposable pattern via alternating maximization.

    Initialized from the greedy both-axes baseline (independent per-axis
    top-N), then each half-step recomputes one axis's exact per-block top-N
    restricted to the OTHER axis's kept set. Every half-step maximizes the
    preserved magnitude given the other axis, so the objective is monotone
    non-decreasing — the result preserves >= the greedy baseline by
    construction. Returns (keep_in (I,), keep_out (O,))."""
    keep_in = _topn_per_block(scores.sum(axis=1), n, m)
    keep_out = _topn_per_block(scores.sum(axis=0), n, m)
    for _ in range(iters):
        keep_in = _topn_per_block(scores @ keep_out.astype(scores.dtype), n, m)
        keep_out = _topn_per_block(keep_in.astype(scores.dtype) @ scores, n, m)
    return keep_in, keep_out


# -------------------------------------------------------------- projection


def project_masks(
    params: PyTree,
    masks: PyTree,
    n: int,
    m: int,
    transposable: bool = True,
) -> tuple[PyTree, dict]:
    """Project every eligible mask leaf onto its best N:M pattern.

    Scores are |w * mask| (already-pruned weights score 0, so the pattern
    spends its N-per-block budget on surviving magnitude). The new mask is
    ``old_mask ∧ (keep_in ⊗ keep_out)`` — monotone, so the IMP ladder's
    no-resurrection invariant holds. Layers whose OUTPUT width does not
    divide by M degrade to input-axis-only (recorded in the report);
    non-divisible CONTRACTION widths raise NMError (check_divisibility
    fails fast at harness init for exactly this).

    Returns (new_masks, report) where report carries per-layer axes/notes
    and the preserved-magnitude fraction vs the pre-projection masks.
    """
    eligible = {name: (shape, s) for name, shape, s in eligible_layers(masks)}
    layers: dict[str, dict] = {}
    mag_before = 0.0
    mag_after = 0.0

    flat_params = {
        path_name(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }

    def project_leaf(path, mask):
        nonlocal mag_before, mag_after
        if mask is None:
            return None
        name = path_name(path)
        if name not in eligible:
            return mask
        shape, s = eligible[name]
        i, o = _matrix_view(shape, s)
        if i % m:
            raise NMError(
                f"layer {name!r}: contraction width {i} not divisible by "
                f"M={m}"
            )
        w = flat_params[name]
        scores = (
            jnp.abs(w.astype(jnp.float32)) * mask.astype(jnp.float32)
        ).reshape(i, o)
        # Output-axis pattern only when the axis is at least two M-blocks
        # wide: at o == M the "pattern" would simply delete N out of M
        # output units outright (for a classifier head: whole class
        # logits), and the transposable payoff — reduced dx/dw GEMMs — is
        # negligible at such widths anyway.
        both_axes = transposable and o % m == 0 and o >= 2 * m
        if both_axes:
            keep_in, keep_out = nm_pattern_transposable(scores, n, m)
        else:
            keep_in = nm_pattern_inaxis(scores, n, m)
            keep_out = jnp.ones((o,), jnp.bool_)
        pattern = keep_in[:, None] & keep_out[None, :]
        new_mask = mask & pattern.reshape(shape)
        mag_before += float(scores.sum())
        mag_after += float(jnp.where(pattern, scores, 0.0).sum())
        layers[name] = {
            "numel": int(mask.size),
            "axes": "both" if both_axes else "in",
            "note": (
                ""
                if both_axes or not transposable
                else f"output width {o} (M={m}): input-axis-only"
            ),
        }
        return new_mask

    new_masks = jax.tree_util.tree_map_with_path(
        project_leaf, masks, is_leaf=lambda x: x is None
    )
    report = {
        "pattern": f"{n}:{m}",
        "transposable": transposable,
        "layers": layers,
        "preserved_magnitude_frac": (
            mag_after / mag_before if mag_before > 0 else 1.0
        ),
    }
    return new_masks, report
