"""Compact-as-you-train: slice the WHOLE train state, not just params.

``compact_params`` (compact.py) serves eval: masks are folded and the
optimizer is gone. Training a physically smaller model needs more:

  - params stay RAW (they keep evolving) and the sliced mask tree rides
    along, so ``apply_masks`` inside the jitted step keeps scattered zeros
    inside kept channels pinned exactly as the dense run would;
  - optimizer moments (Adam mu/nu, SGD trace, schedule-free z) mirror the
    params tree inside optax's state tuples and must slice with the SAME
    keep vectors — JaxPruner's "sparsity threads through the whole train
    state" design (PAPERS.md);
  - BN running stats slice along stats_keep;
  - and the whole thing must round-trip: ``expand_train_state`` scatters a
    trained small state back into full coordinates so weight rewind, the
    next level's GLOBAL magnitude threshold, and checkpoints never learn
    that the level ran small.

Optax states are (named)tuples wrapping params-shaped subtrees next to
scalar bookkeeping (count, ScaleByScheduleState). The walker below aligns
leaves by PATH SUFFIX: an opt_state leaf whose trailing dict keys spell a
params leaf path (…/mu/layer1_0/Conv_0/kernel ↔ layer1_0/Conv_0/kernel)
and whose sliced axes have the expected sizes gets the params leaf's
slice; everything else passes through untouched. A suffix match with the
WRONG axis size raises — that means an optimizer state we don't
understand, and silently passing it through would corrupt training.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from .compact import (
    CompactionPlan,
    _expand_leaf,
    _np,
    _slice_leaf,
    compact_stats,
    compact_tree,
    expand_stats,
    expand_tree,
)
from .graph import PathT


def _path_str(entry) -> Optional[str]:
    """String component of a key-path entry (DictKey/GetAttrKey), else None
    (SequenceKey/FlattenedIndexKey tuple positions)."""
    for attr in ("key", "name"):
        v = getattr(entry, attr, None)
        if isinstance(v, str):
            return v
    return None


def _leaf_specs(plan: CompactionPlan) -> dict[PathT, tuple]:
    """params leaf path -> (in_keep | None, out_keep | None), sliced only."""
    specs: dict[PathT, tuple] = {}
    for path in set(plan.in_keep) | set(plan.out_keep):
        specs[path] = (plan.in_keep.get(path), plan.out_keep.get(path))
    return specs


def _map_opt_state(opt_state: Any, plan: CompactionPlan, expand: bool):
    """Slice (or expand) every params-aligned leaf of an optax state."""
    specs = _leaf_specs(plan)
    flat, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    out = []
    for path, leaf in flat:
        comps = [c for c in (_path_str(e) for e in path) if c is not None]
        match = None
        for n in range(len(comps), 0, -1):
            cand = tuple(comps[-n:])
            if cand in specs:
                match = specs[cand]
                break
        if match is None:
            out.append(leaf)
            continue
        ik, ok = match
        arr = _np(leaf)
        # Axis-size guard: moments mirror the params leaf exactly; anything
        # else with the same trailing path is a structure we don't know.
        want_in = None if ik is None else (int(ik.sum()) if expand else ik.size)
        want_out = None if ok is None else (int(ok.sum()) if expand else ok.size)
        if (want_in is not None and (arr.ndim < 2 or arr.shape[-2] != want_in)) or (
            want_out is not None and (arr.ndim < 1 or arr.shape[-1] != want_out)
        ):
            raise ValueError(
                f"opt_state leaf {'/'.join(comps)} matches a sliced params "
                f"path but has shape {arr.shape} — unrecognized optimizer "
                "state layout; refusing to slice it blindly"
            )
        out.append(
            _expand_leaf(arr, ik, ok) if expand else _slice_leaf(arr, ik, ok)
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def slice_opt_state(opt_state: Any, plan: CompactionPlan) -> Any:
    """Slice Adam mu/nu, SGD trace, schedule-free z, … with the plan;
    scalar bookkeeping (count, schedule state) passes through."""
    return _map_opt_state(opt_state, plan, expand=False)


def expand_opt_state(opt_state: Any, plan: CompactionPlan) -> Any:
    """Inverse: removed coordinates come back as zero moments — exactly the
    moments a fresh per-level ``tx.init`` would give them, and (with zero
    data gradient at fully-masked coordinates) what the dense run holds
    when weight decay is off."""
    return _map_opt_state(opt_state, plan, expand=True)


def compact_train_state(state, plan: CompactionPlan):
    """Physically shrink a TrainState for one level of compact training.

    params stay raw (NOT mask-folded); the mask tree is sliced alongside so
    the small train step's ``apply_masks`` semantics match the dense run.
    step/rng carry over unchanged."""
    return state.replace(
        params=compact_tree(state.params, plan),
        masks=compact_tree(state.masks, plan),
        batch_stats=compact_stats(state.batch_stats, plan),
        opt_state=slice_opt_state(state.opt_state, plan),
    )


def expand_train_state(state, plan: CompactionPlan, anchor=None):
    """Scatter a trained small state back into full coordinates.

    With ``anchor`` (the full-coordinate state captured at compaction
    time — i.e. the level's post-rewind start state):

      - params: kept coordinates take the trained values; REMOVED
        coordinates take the anchor's — a removed channel's consumer
        in-rows hold real magnitudes that the next level's global top-k
        must still see (zeros would silently re-rank the threshold);
      - masks: the anchor mask tree verbatim (masks never change during a
        level; slicing was lossy for consumer in-rows of dead channels);
      - batch_stats: kept entries trained, removed entries anchored — a
        removed channel's residue stays exactly the zero it was proven to
        be at slice time;
      - opt_state: removed moments are zeros (see expand_opt_state).

    Without an anchor, removed coordinates are zeros everywhere (the pure
    inverse; property-tested)."""
    params = expand_tree(
        state.params, plan, anchor=None if anchor is None else anchor.params
    )
    if anchor is not None:
        masks = anchor.masks
    else:
        masks = expand_tree(state.masks, plan)
    stats = expand_stats(
        state.batch_stats,
        plan,
        anchor=None if anchor is None else anchor.batch_stats,
    )
    return state.replace(
        params=params,
        masks=masks,
        batch_stats=stats,
        opt_state=expand_opt_state(state.opt_state, plan),
    )


def width_signature(plan: CompactionPlan) -> list:
    """JSON-serializable width signature for multihost agreement."""
    return sorted(
        [str(k), int(v)] for k, v in dict(plan.width_overrides).items()
    )


__all__ = [
    "compact_train_state",
    "expand_train_state",
    "expand_opt_state",
    "slice_opt_state",
    "width_signature",
]
