"""Gathered N:M execution — run projected masks at reduced GEMM width.

The execution half of the N:M backend (projection: nm.py). For a layer
whose mask is ``keep_in ⊗ keep_out`` separable (what ``project_masks``
produces), the kept weights of each M-block gather into dense
``[.., K·N/M]`` tensors via a STATIC int32 index map baked into the module
as metadata — compile-time constants, so one executable per (level, shape)
exactly like the compaction caches, and zero steady-state recompiles.

The custom-VJP matmul is the core trick. Pure autodiff through the gathers
would transpose them into XLA scatters on the full-size kernel gradient —
measured 0.7x (SLOWER than masked-dense) on CPU for large fc layers. The
custom backward instead computes:

  dw = xᵀ @ dy        — the full GEMM, IDENTICAL to masked-dense's dw
                        expression. The true gradient of the gathered
                        forward is zero outside keep_in ⊗ keep_out; those
                        entries are restored to zero by the mask factor the
                        ``apply_masks`` chain rule contributes outside the
                        module, so the grads that reach the optimizer match
                        masked-dense EXACTLY (asserted in tests/test_nm.py).
  dx = scatter(dyg @ wgᵀ) — reduced by BOTH axes (the transposable win);
                        the scatter target is only [B, I], not [I, O].

Forward and dx run at N/M width; dw stays a full GEMM (same cost as
masked-dense, not worse). Measured on this box (fp32, 2:4): forward
1.2-4.5x, fwd+bwd 1.1-1.5x over masked-dense across ViT-MLP and VGG-fc
shapes.

Modules mirror their dense counterparts' param trees exactly (NMDense ~
nn.Dense, NMDenseGeneral ~ nn.DenseGeneral, NMConv1x1 ~ 1x1 nn.Conv,
NMSelfAttention ~ nn.MultiHeadDotProductAttention) so checkpoints, masks
and the pruning predicate are interchangeable — the same contract the
ring/flash attention impls keep (models/vit.py:_project_qkv_padded).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.masking import PyTree, path_name
from .nm import _matrix_view, eligible_layers

# Route a layer through the gathered path only when the index map drops at
# least this fraction of the contraction axis — below that the gather
# overhead eats the reduced-GEMM win. Any projected N:M pattern clears it
# (N/M <= 1/2); dense level-0 masks never route.
MIN_AXIS_SAVINGS = 0.25

# Executable-surface hook: plan-signature kind for gathered N:M execution.
# analysis/exec_manifest.py enumerates every PLAN_SIGNATURE_KIND declaration
# in the package to bound the plan-format vocabulary of AOT cache keys.
PLAN_SIGNATURE_KIND = "nm"


# ------------------------------------------------------------- the matmul


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def nm_matmul(ki: tuple, ko: Optional[tuple], x2, w2, b):
    """y = x2 @ w2 + b computed at reduced width via static gathers.

    ``ki``/``ko`` are compile-time int tuples of the live rows/columns of
    the (already mask-multiplied) 2D kernel ``w2[I, O]``; ``ko=None`` means
    the output axis is full (non-transposable pattern). Dropped output
    columns still produce their bias value, exactly like masked-dense."""
    return _nm_fwd(ki, ko, x2, w2, b)[0]


def _nm_fwd(ki, ko, x2, w2, b):
    ki_a = jnp.asarray(ki, jnp.int32)
    xg = jnp.take(x2, ki_a, axis=1)
    wg = jnp.take(w2, ki_a, axis=0)
    if ko is None:
        y = xg @ wg + b
    else:
        ko_a = jnp.asarray(ko, jnp.int32)
        z = xg @ jnp.take(wg, ko_a, axis=1) + jnp.take(b, ko_a)
        y = jnp.broadcast_to(b, (x2.shape[0], b.shape[0])).at[:, ko_a].set(z)
    return y, (x2, w2)


def _nm_bwd(ki, ko, res, dy):
    x2, w2 = res
    ki_a = jnp.asarray(ki, jnp.int32)
    wg = jnp.take(w2, ki_a, axis=0)
    if ko is None:
        dyg = dy
    else:
        ko_a = jnp.asarray(ko, jnp.int32)
        wg = jnp.take(wg, ko_a, axis=1)
        dyg = jnp.take(dy, ko_a, axis=1)
    # dx: reduced GEMM + small [B, I] scatter. Rows outside ki are all-zero
    # in the mask, so masked-dense's dx is zero there too — exact match.
    dx = (
        jnp.zeros_like(x2)
        .at[:, ki_a]
        .set((dyg @ wg.T).astype(x2.dtype))
    )
    # dw: full GEMM, deliberately NOT the literal gradient of the gathered
    # forward (zero outside ki x ko). The apply_masks chain multiplies this
    # by the mask outside the module, zeroing exactly those entries — so
    # the optimizer sees masked-dense's dw bit-for-bit in structure, and
    # the XLA scatter a gathered dw would need (0.7x, see module docstring)
    # never exists.
    dw = (x2.T @ dy).astype(w2.dtype)
    db = dy.sum(axis=0).astype(dy.dtype)
    return dx, dw, db


nm_matmul.defvjp(_nm_fwd, _nm_bwd)


# -------------------------------------------------------------- the layers


class NMDense(nn.Module):
    """nn.Dense drop-in with gathered N:M execution (same param tree)."""

    features: int
    kept_in: tuple
    kept_out: Optional[tuple] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (in_features, self.features)
        )
        bias = self.param("bias", nn.initializers.zeros_init(), (self.features,))
        x, kernel, bias = (a.astype(self.dtype) for a in (x, kernel, bias))
        lead = x.shape[:-1]
        y = nm_matmul(
            self.kept_in, self.kept_out, x.reshape(-1, in_features), kernel, bias
        )
        return y.reshape(*lead, self.features)


class NMDenseGeneral(nn.Module):
    """nn.DenseGeneral drop-in for the flax-MHA kernel layouts.

    Supports the two layouts the attention stack uses: ``axis=-1`` with
    tuple features (qkv: kernel (D, H, hd)) and ``axis=(-2, -1)`` with int
    features (out: kernel (H, hd, D)). The contraction runs on the 2D
    matrix view with the same static gathers as NMDense."""

    features: Any
    kept_in: tuple
    kept_out: Optional[tuple] = None
    axis: Any = -1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = (
            tuple(self.features)
            if isinstance(self.features, (tuple, list))
            else (self.features,)
        )
        axis = (
            tuple(self.axis) if isinstance(self.axis, (tuple, list)) else (self.axis,)
        )
        axis = tuple(sorted(a % x.ndim for a in axis))
        if axis != tuple(range(x.ndim - len(axis), x.ndim)):
            raise ValueError(
                f"NMDenseGeneral supports trailing contraction axes only, "
                f"got axis={self.axis}"
            )
        contract_shape = tuple(x.shape[a] for a in axis)
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), contract_shape + features
        )
        bias = self.param("bias", nn.initializers.zeros_init(), features)
        x, kernel, bias = (a.astype(self.dtype) for a in (x, kernel, bias))
        i = int(np.prod(contract_shape))
        o = int(np.prod(features))
        lead = x.shape[: x.ndim - len(axis)]
        y = nm_matmul(
            self.kept_in,
            self.kept_out,
            x.reshape(-1, i),
            kernel.reshape(i, o),
            bias.reshape(o),
        )
        return y.reshape(*lead, *features)


class NMConv1x1(nn.Module):
    """1x1 nn.Conv drop-in: a 1x1 convolution IS a matmul over channels, so
    the gathered path applies directly. Param tree matches nn.Conv (kernel
    (1, 1, C, O)); strides subsample spatially before the contraction
    (VALID 1x1 semantics)."""

    features: int
    kept_in: tuple
    kept_out: Optional[tuple] = None
    strides: tuple = (1, 1)
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (1, 1, c, self.features))
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,)
            )
        else:
            # nm_matmul's vjp structure needs a bias operand; a constant
            # zero adds nothing to the forward and its db is discarded.
            bias = jnp.zeros((self.features,))
        x = x[:, :: self.strides[0], :: self.strides[1], :]
        x, kernel, bias = (a.astype(self.dtype) for a in (x, kernel, bias))
        n, h, w, _ = x.shape
        y = nm_matmul(
            self.kept_in,
            self.kept_out,
            x.reshape(-1, c),
            kernel.reshape(c, self.features),
            bias,
        )
        return y.reshape(n, h, w, self.features)


class NMSelfAttention(nn.Module):
    """Dense self-attention with gathered qkv/out projections.

    Identical param tree to ``nn.MultiHeadDotProductAttention`` (the same
    contract RingSelfAttention/FlashSelfAttention keep); projections
    without a hook fall back to plain nn.DenseGeneral under the same name.
    Attention dropout is not supported (the DeiT configs use attn_drop=0;
    EncoderBlock rejects the combination loudly)."""

    num_heads: int
    # Hashable hook map: (("query", (ki, ko)), ("out", (ki, ko)), ...)
    nm: tuple = ()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = self.num_heads
        hd = d // h
        hooks = dict(self.nm)

        def proj(name, features, axis=-1):
            hook = hooks.get(name)
            if hook is None:
                return nn.DenseGeneral(
                    features, axis=axis, dtype=self.dtype, name=name
                )
            ki, ko = hook
            return NMDenseGeneral(
                features=features,
                kept_in=ki,
                kept_out=ko,
                axis=axis,
                dtype=self.dtype,
                name=name,
            )

        q = proj("query", (h, hd))(x)
        k = proj("key", (h, hd))(x)
        v = proj("value", (h, hd))(x)
        out = nn.dot_product_attention(q, k, v, dtype=self.dtype)
        return proj("out", d, axis=(-2, -1))(out)


# ------------------------------------------------------------ plan builder


@dataclasses.dataclass
class NMExecPlan:
    """Static routing decision for one level: which layers run gathered and
    with which index maps. Pure function of the masks + model family, so
    every host derives the identical plan from its replicated masks."""

    # model-hook key -> (kept_in tuple, kept_out tuple | None)
    overrides: dict
    report: dict

    def as_override_tuple(self) -> tuple:
        """Hashable form for step-cache keys and Module metadata."""
        return tuple(sorted(self.overrides.items()))

    def plan_signature(self) -> tuple:
        """(kind, overrides) executable-cache signature: the plan component
        of the serving engine's AOT key (serve/fleet/aot_cache.py)."""
        return (PLAN_SIGNATURE_KIND, self.as_override_tuple())


def _hook_key(model, name: str, shape: tuple) -> Optional[str]:
    """Map a mask path to the model's nm_overrides hook key; None = the
    layer has no gathered-execution hook (it stays masked-dense and shows
    up as unrouted coverage)."""
    from ..models.densenet import DenseNet
    from ..models.resnet import Bottleneck, ResNet
    from ..models.vgg import VGG
    from ..models.vit import VisionTransformer

    key = name[: -len("/kernel")] if name.endswith("/kernel") else name
    if isinstance(model, VisionTransformer):
        parts = key.split("/")
        if key in ("head", "head_dist"):
            return key
        if len(parts) == 3 and parts[1] == "mlp" and parts[2] in ("fc1", "fc2"):
            return key
        if (
            len(parts) == 3
            and parts[1] == "attn"
            and parts[2] in ("query", "key", "value", "out")
            # Only the dense impl takes projection hooks; flash keeps its
            # fused qkv path (ring falls back to dense before this runs).
            and model.attention_impl == "dense"
        ):
            return key
        return None
    if isinstance(model, VGG):
        return key if key in ("fc0", "fc1", "fc2") else None
    if isinstance(model, ResNet):
        if key == "fc":
            return key
        # Bottleneck's leading 1x1 conv (non-residual, stride 1). The
        # expansion 1x1 and downsample convs stay masked-dense: their
        # outputs are residual-shared and not worth the extra wiring.
        if (
            model.block_cls is Bottleneck
            and key.endswith("/Conv_0")
            and len(shape) == 4
        ):
            return key
        return None
    if isinstance(model, DenseNet):
        return key if key == "classifier" else None
    return None


def build_nm_plan(model, masks: PyTree, min_axis_savings: float = MIN_AXIS_SAVINGS):
    """Derive the gathered-execution plan from the LIVE masks.

    Live-row/col detection (a row/column with any surviving weight) rather
    than re-deriving the projected pattern: after compact_train slices
    channels out, block alignment is gone, but liveness is still exact —
    the gathered contraction only needs the index map to cover every
    nonzero, which the live set does by construction. This is what makes
    the two backends composable (channel-compact first, N:M the survivors).
    """
    from ..ops.masking import mask_leaves_with_path

    flat_masks = {
        path_name(p): m for p, m in mask_leaves_with_path(masks)
    }
    overrides: dict = {}
    layers: dict = {}
    eligible_params = 0
    routed_params = 0
    for name, shape, s in eligible_layers(masks):
        i, o = _matrix_view(shape, s)
        numel = int(np.prod(shape))
        eligible_params += numel
        key = _hook_key(model, name, shape)
        m2 = np.asarray(jax.device_get(flat_masks[name])).reshape(i, o)
        live_in = np.nonzero(m2.any(axis=1))[0]
        live_out = np.nonzero(m2.any(axis=0))[0]
        routed = (
            key is not None
            and len(live_in) <= i * (1.0 - min_axis_savings)
        )
        if routed:
            kept_out = (
                tuple(int(x) for x in live_out) if len(live_out) < o else None
            )
            overrides[key] = (tuple(int(x) for x in live_in), kept_out)
            routed_params += numel
        layers[name] = {
            "numel": numel,
            "routed": routed,
            "hookable": key is not None,
            "kept_in_frac": len(live_in) / i,
            "kept_out_frac": len(live_out) / o,
        }
    report = {
        "eligible_params": eligible_params,
        "routed_params": routed_params,
        "coverage_frac": (
            routed_params / eligible_params if eligible_params else 0.0
        ),
        "layers": layers,
    }
    return NMExecPlan(overrides=overrides, report=report)
