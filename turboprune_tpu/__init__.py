"""TurboPrune-TPU: a TPU-native lottery-ticket / pruning training framework.

A ground-up JAX/XLA re-design of the capabilities of TurboPrune
(nelaturuharsha/TurboPrune): iterative magnitude pruning (IMP with weight /
learning-rate rewinding), pruning-at-initialization (SNIP, SynFlow, ER-ERK,
ER-balanced), random ERK/balanced iterative pruning, and cyclic training
schedules for ResNet / VGG / ViT(DeiT) on CIFAR-10/100 and ImageNet.

Design (vs. the reference's PyTorch DDP + FFCV stack):
  - masks are pytrees mirroring the prunable params, applied as ``w * m``
    inside the jit-compiled forward (reference: mask buffers in custom
    ``nn.Module`` subclasses, utils/mask_layers.py)
  - pruning criteria are pure functions ``(params, masks, ...) -> masks``
    (reference: in-place module walks, utils/pruning_utils.py)
  - data parallelism is SPMD via ``jax.sharding`` over a device mesh with XLA
    collectives on ICI/DCN (reference: DDP + NCCL, utils/distributed_utils.py)
  - the input pipeline is device-resident CIFAR + a grain/tf.data ImageNet
    loader (reference: airbench GPU loader + FFCV, utils/dataset.py)
  - checkpoints are Orbax pytrees with the same artifact roles
    (init / rewind / level_k) (reference: torch.save, utils/harness_utils.py)
"""

__version__ = "0.1.0"
