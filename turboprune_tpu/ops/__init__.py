from . import masking
from .flash import flash_attention
from .masking import (
    apply_masks,
    global_threshold_mask,
    is_prunable_path,
    layerwise_sparsity,
    make_masks,
    mask_leaves,
    mask_leaves_with_path,
    mask_where,
    num_prunable,
    overall_density,
    overall_sparsity,
    path_name,
    per_layer_threshold_mask,
    reset_masks,
)

__all__ = [
    "masking",
    "flash_attention",
    "apply_masks",
    "global_threshold_mask",
    "is_prunable_path",
    "layerwise_sparsity",
    "make_masks",
    "mask_leaves",
    "mask_leaves_with_path",
    "mask_where",
    "num_prunable",
    "overall_density",
    "overall_sparsity",
    "path_name",
    "per_layer_threshold_mask",
    "reset_masks",
]
