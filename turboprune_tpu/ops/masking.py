"""Mask pytrees — the sparsity mechanism.

The reference stores masks as buffers on custom module subclasses and
multiplies ``mask * weight`` in every forward
(/root/reference/utils/mask_layers.py:25,69,109). Here masks are a pytree
mirroring the model params, with a boolean array at every *prunable* leaf
(conv / dense kernels — reference masks every Conv2d and Linear, including
the classifier head, custom_models.py:217-220) and ``None`` elsewhere.
``apply_masks`` multiplies them into the params inside the jitted forward, so
XLA fuses the multiply into the convolution's operand producer; gradients
flow to the raw params scaled by the mask exactly as in the reference
(pruned weights get zero gradient from the forward but can still drift via
momentum / weight decay — a semantic we preserve, SURVEY.md §3.3).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp

PyTree = Any

# Treat None as a leaf so mask trees (None at non-prunable positions) keep the
# exact structure of the param tree.
def _is_none(x) -> bool:
    return x is None


def is_prunable_path(path: tuple) -> bool:
    """A param leaf is prunable iff it is a conv/dense kernel.

    Flax linen names conv and dense weights 'kernel'; biases are 'bias' and
    norm params 'scale'/'bias' — matching the reference's rule of masking
    exactly the Conv2d/Linear weights (custom_models.py:217-220)."""
    last = path[-1]
    key = getattr(last, "key", getattr(last, "name", str(last)))
    return str(key) == "kernel"


def tree_paths(tree: PyTree) -> Iterator[tuple]:
    for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        yield path


def path_name(path: tuple) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p)))))
    return "/".join(parts)


def make_masks(
    params: PyTree, predicate: Callable[[tuple], bool] = is_prunable_path
) -> PyTree:
    """Dense (all-ones) mask tree: bool ones at prunable leaves, None elsewhere."""

    def leaf_mask(path, leaf):
        if predicate(path):
            return jnp.ones(jnp.shape(leaf), dtype=jnp.bool_)
        return None

    return jax.tree_util.tree_map_with_path(leaf_mask, params)


def apply_masks(params: PyTree, masks: PyTree) -> PyTree:
    """``w * m`` at masked leaves; identity elsewhere. Call inside jit."""

    def apply(m, p):
        if m is None:
            return p
        return p * m.astype(p.dtype)

    return jax.tree.map(apply, masks, params, is_leaf=_is_none)


def mask_where(masks: PyTree, fn: Callable[..., jax.Array], *trees: PyTree) -> PyTree:
    """Map ``fn(mask, *leaves)`` over masked positions only; None passthrough."""

    def go(m, *leaves):
        if m is None:
            return None
        return fn(m, *leaves)

    return jax.tree.map(go, masks, *trees, is_leaf=_is_none)


def mask_leaves(masks: PyTree) -> list[jax.Array]:
    return [m for m in jax.tree.leaves(masks, is_leaf=_is_none) if m is not None]


def mask_leaves_with_path(masks: PyTree) -> list[tuple[tuple, jax.Array]]:
    out = []
    for path, m in jax.tree_util.tree_flatten_with_path(
        masks, is_leaf=_is_none
    )[0]:
        if m is not None:
            out.append((path, m))
    return out


def num_prunable(masks: PyTree) -> int:
    return sum(int(m.size) for m in mask_leaves(masks))


def overall_sparsity(masks: PyTree) -> float:
    """Percent of prunable weights masked out (reference
    PruneModel.get_overall_sparsity, custom_models.py:51-62 — returns %)."""
    total = 0
    zeros = 0
    for m in mask_leaves(masks):
        total += int(m.size)
        zeros += int(m.size - jnp.sum(m))
    return (zeros / total) * 100.0 if total else 0.0


def overall_density(masks: PyTree) -> float:
    return 1.0 - overall_sparsity(masks) / 100.0


def layerwise_sparsity(masks: PyTree) -> dict[str, float]:
    """Per-layer sparsity %, keyed by param path (reference
    print_layer_sparsity, custom_models.py:29-49)."""
    out = {}
    for path, m in mask_leaves_with_path(masks):
        zeros = int(m.size - jnp.sum(m))
        out[path_name(path)] = (zeros / m.size) * 100.0
    return out


def reset_masks(masks: PyTree) -> PyTree:
    """All-ones masks of the same structure (reference reset_masks,
    custom_models.py:148-151)."""
    return mask_where(masks, lambda m: jnp.ones_like(m))


def combine_rewind(
    current_params: PyTree, rewind_params: PyTree, masks: PyTree
) -> PyTree:
    """Weight rewinding: restore ALL params from the rewind checkpoint.

    The reference restores every non-mask tensor (custom_models.py:137-144);
    masks live in a separate tree here, so this is a full param swap — kept as
    a named op so the call site documents intent."""
    del current_params, masks
    return rewind_params


def global_threshold_mask(
    scores: PyTree, masks: PyTree, density: float
) -> PyTree:
    """Global magnitude-style masking: keep weights whose score exceeds the
    k-th smallest score, k = (1-density) * N over ALL prunable weights
    (reference prune_mag, pruning_utils.py:61-89: global kthvalue then
    ``mask = score > threshold``).

    Scores at already-pruned positions must be 0 (callers multiply by the
    mask) so pruning is monotone across levels. When k < 1 the reference
    leaves the masks untouched (pruning_utils.py:81) — replicated here; the
    density is a host-side float so k is static.

    The threshold (k-th smallest = (n-k+1)-th largest) comes from
    ``lax.top_k`` over kept+1 elements instead of a full ``jnp.sort``:
    identical value, so the masks are bit-identical to the sort path
    (asserted in tests), but the partial selection scales with the KEPT
    count — at the recipe's 90%+ sparsities that is a 10x+ smaller
    selection problem than sorting all N prunable weights."""
    flat = jnp.concatenate(
        [s.reshape(-1) for s in mask_leaves(scores)]
    ).astype(jnp.float32)
    n = flat.shape[0]
    k = int((1.0 - density) * n)
    if k < 1:
        return masks
    threshold = _kth_smallest(flat, k)
    return mask_where(scores, lambda s: s > threshold)


def _kth_smallest(flat: jax.Array, k: int) -> jax.Array:
    """kthvalue(k) (1-indexed) via ``lax.top_k``: the k-th smallest of n
    values is the smallest of the top (n - k + 1), i.e. the last entry of
    ``top_k(flat, n - k + 1)``. Values are compared exactly (no recompute),
    so the result is bit-identical to ``jnp.sort(flat)[k - 1]``."""
    kept_plus_one = int(flat.shape[0]) - k + 1
    top, _ = jax.lax.top_k(flat, kept_plus_one)
    return top[-1]


def per_layer_threshold_mask(scores: PyTree, densities: dict[str, float]) -> PyTree:
    """Per-layer kthvalue masking used by random_erk / random_balanced
    (reference pruning_utils.py:126-146, 326-347)."""

    def one(path, s):
        d = densities[path_name(path)]
        n = s.size
        k = int((1.0 - d) * n)
        if k <= 0:
            # Keep every position with a positive score. Scores at
            # already-pruned positions are exactly 0 (callers multiply by the
            # mask), so a density-1 layer keeps its existing mask rather than
            # resurrecting pruned weights — the reference's k==0 threshold-0
            # behavior (pruning_utils.py:137-143).
            return s > 0.0
        threshold = _kth_smallest(s.reshape(-1).astype(jnp.float32), k)
        return s > threshold

    return _map_with_path_masked(one, scores)


def _map_with_path_masked(fn, masks_like: PyTree) -> PyTree:
    def go(path, m):
        if m is None:
            return None
        return fn(path, m)

    return jax.tree_util.tree_map_with_path(go, masks_like, is_leaf=_is_none)
