"""First-party Pallas TPU flash attention (forward + backward kernels).

Non-causal multi-head attention with a key-validity mask, computed
blockwise so the S x S score matrix never materializes in HBM: for each
query block the kernel streams key/value blocks through VMEM, carrying the
online-softmax running max/sum in VMEM scratch across the (sequential)
innermost grid dimension — the flash-attention recurrence on the hardware
it was shaped for (MXU matmuls with fp32 accumulators, VPU for the
exp/max/sum, ~(BLOCK x BLOCK) live scores).

The backward pass is two more Pallas kernels over the same block grid
(recompute-based, flash2-style): residuals are just (o, logsumexp), so
training memory stays O(S) per head instead of O(S^2).

Relationship to the rest of the framework:
  - models/vit.py wires this as ``attention_impl: "flash"`` — single-device
    blockwise attention with the SAME param tree as dense/ring.
  - parallel/ring.py is the multi-device complement (sequence sharded over
    the mesh, K/V rotating by ppermute); flash is the within-device answer.
  - The reference has no analog: its DeiT path runs timm's dense attention
    (materialized scores) and was dead code anyway (SURVEY.md §2.1).

On non-TPU backends the kernels run in Pallas interpret mode (exact same
program, executed by XLA ops) — which is how the CPU test suite proves
them, including gradients, against a dense jnp oracle.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e30


def _use_interpret() -> bool:
    """Mosaic lowering needs a real TPU; anything else runs interpreted."""
    return jax.default_backend() not in ("tpu",)


def _dot(a, b):  # [m, k] @ [k, n] with fp32 accumulation on the MXU
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_t0(a, b):  # contract dim 0 of both: [k, m] x [k, n] -> [m, n]
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_t1(a, b):  # contract dim 1 of both: [m, k] x [n, k] -> [m, n]
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, acc, m, l, *,
                scale: float):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, -jnp.inf)
        l[:] = jnp.zeros_like(l)

    q = q_ref[0]  # [Bq, D]
    k = k_ref[0]  # [Bk, D]
    valid = mask_ref[0] > 0  # [Bk]
    s = _dot_t1(q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    s = jnp.where(valid[None, :], s, NEG_BIG)

    m_old = m[:]  # [Bq, 1]
    m_new = jnp.maximum(m_old, s.max(axis=1, keepdims=True))
    p = jnp.exp(s - m_new) * valid[None, :]
    corr = jnp.exp(m_old - m_new)
    l[:] = l[:] * corr + p.sum(axis=1, keepdims=True)
    acc[:] = acc[:] * corr + _dot(p.astype(v_ref.dtype), v_ref[0])
    m[:] = m_new

    @pl.when(ki == nk - 1)
    def _():
        lsafe = jnp.maximum(l[:], 1e-30)
        o_ref[0] = (acc[:] / lsafe).astype(o_ref.dtype)
        lse_ref[0] = m[:] + jnp.log(lsafe)


def _flash_fwd(q, k, v, mask, scale, block_q, block_k, interpret):
    bh, s_len, d = q.shape
    nq, nk = s_len // block_q, s_len // block_k
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k), lambda b, qi, ki: (0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask)
    return o, lse


# ----------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, drow_ref,
               dq_ref, dq_acc, *, scale: float):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    valid = mask_ref[0] > 0
    s = _dot_t1(q * scale, k)
    s = jnp.where(valid[None, :], s, NEG_BIG)
    p = jnp.exp(s - lse_ref[0]) * valid[None, :]  # [Bq, Bk]
    dp = _dot_t1(do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32))
    ds = p * (dp - drow_ref[0]) * scale  # [Bq, Bk]
    dq_acc[:] = dq_acc[:] + _dot(ds, k)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, drow_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float):
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    valid = mask_ref[0] > 0
    s = _dot_t1(q * scale, k)
    s = jnp.where(valid[None, :], s, NEG_BIG)
    p = jnp.exp(s - lse_ref[0]) * valid[None, :]  # [Bq, Bk]
    dv_acc[:] = dv_acc[:] + _dot_t0(p, do)  # [Bk, D]
    dp = _dot_t1(do, v_ref[0].astype(jnp.float32))
    ds = p * (dp - drow_ref[0]) * scale
    dk_acc[:] = dk_acc[:] + _dot_t0(ds, q)  # [Bk, D]

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ------------------------------------------------------------------- public
@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7)
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_valid: jax.Array,
    scale: float,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise (flash) attention. q/k/v: [batch*heads, seq, head_dim];
    ``block_q``/``block_k`` must divide ``seq`` (pad the sequence up to a
    block multiple first — models/vit.py FlashSelfAttention does). kv_valid:
    [1, seq] (0/1) marking real key rows. Returns the same shape as q."""
    o, _ = _fa_fwd(q, k, v, kv_valid, scale, block_q, block_k, interpret)
    return o


def _fa_fwd(q, k, v, kv_valid, scale, block_q, block_k, interpret):
    s_len = q.shape[1]
    if s_len % block_q or s_len % block_k:
        raise ValueError(
            f"flash_attention: seq {s_len} must be a multiple of "
            f"block_q={block_q} and block_k={block_k} — pad the sequence "
            "(the grid floor-divides and would silently drop the tail)"
        )
    if kv_valid.shape != (1, s_len):
        raise ValueError(
            f"flash_attention: kv_valid must have shape (1, {s_len}), got "
            f"{kv_valid.shape} — the mask is shared across the batch "
            "(a per-example mask would be silently ignored)"
        )
    if interpret is None:
        interpret = _use_interpret()
    mask = kv_valid.astype(jnp.float32)
    o, lse = _flash_fwd(q, k, v, mask, scale, block_q, block_k, interpret)
    return o, (q, k, v, mask, o, lse)


def _fa_bwd(scale, block_q, block_k, interpret, residuals, g):
    if interpret is None:
        interpret = _use_interpret()
    q, k, v, mask, o, lse = residuals
    bh, s_len, d = q.shape
    nq, nk = s_len // block_q, s_len // block_k
    # D_i = sum_d do * o — per (row) softmax-derivative correction term.
    drow = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                   keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k), lambda b, qi, ki: (0, ki)),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, mask, g, lse, drow)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k), lambda b, ki, qi: (0, ki)),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, ki, qi: (b, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask, g, lse, drow)
    return dq, dk, dv, None


flash_attention.defvjp(_fa_fwd, _fa_bwd)
