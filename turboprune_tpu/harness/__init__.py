"""Training harnesses (reference layer:
/root/reference/harness_definitions/)."""

from .cyclic_harness import CyclicPruningHarness
from .pruning_harness import PruningHarness

__all__ = ["PruningHarness", "CyclicPruningHarness"]
