"""PruningHarness — the training runtime.

Rebuilds the reference harness stack (BaseHarness + PruningHarness,
/root/reference/harness_definitions/base_harness.py:32-305,
standard_pruning_harness.py:25-275) as one class around a jitted SPMD step:

  - model / loaders / mesh built from config (reference _create_model /
    _setup_dataloaders, standard_pruning_harness.py:128-157)
  - ``train_one_level(epochs_per_level, level)`` owns the inner loop:
    per-level optimizer + schedule re-init, level-0 init/rewind artifact
    saves, per-epoch train + test, CSV/rich metric logging
    (standard_pruning_harness.py:159-269)
  - the hot loop is ONE compiled program per step: forward (masked weights),
    backward, psum over the data mesh axis, optimizer update — where the
    reference had DDP allreduce + autocast + host-side scheduler.step()
    (base_harness.py:115-134,178-188)

Metric sums stay on device during an epoch (loss*n / correct / n) and are
pulled once at epoch end — the reference pays a host sync every step for
wandb lr logging (base_harness.py:129-130); here async dispatch runs free.

No per-level recompiles: the step function is cached by (total_steps) —
same epoch budget every level means the level-1 compile is reused for all
subsequent levels (SURVEY.md §7 "Recompile hazards").
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..config.schema import MainConfig
from ..data import create_loaders
from ..models import create_model
from ..ops import masking
from ..parallel import (
    assemble_batch,
    assemble_chunk,
    assert_width_agreement,
    create_mesh,
    is_primary,
    epoch_sharding,
    make_sharded_eval_step,
    make_sharded_scan_chunk,
    make_sharded_scan_epoch,
    make_sharded_scan_eval,
    make_sharded_train_step,
    replicate,
)
from ..train import (
    TrainState,
    create_optimizer,
    create_schedule,
    create_train_state,
    eval_params,
    make_eval_step,
    make_scan_chunk,
    make_scan_epoch,
    make_scan_eval,
    make_train_step,
)
from ..utils import (
    MID_LEVEL,
    MODEL_INIT,
    MODEL_REWIND,
    OPTIMIZER_INIT,
    OPTIMIZER_REWIND,
    ExperimentCheckpoints,
    MetricsLogger,
    config_fingerprint,
    display_training_info,
)
from ..utils.wandb_logging import WandbRun

PRECISION_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float32": jnp.float32,
}


class PruningHarness:
    """Concrete trainer for one experiment (reference PruningHarness,
    standard_pruning_harness.py:25)."""

    def __init__(
        self,
        cfg: MainConfig,
        expt_dir: tuple[str, str],
        loaders: Optional[Any] = None,
        state: Optional[TrainState] = None,
    ):
        self.cfg = cfg
        self.prefix, self.expt_dir = expt_dir
        ep = cfg.experiment_params
        self.compute_dtype = PRECISION_DTYPES[ep.training_precision]

        self.mesh = create_mesh(
            num_devices=ep.num_devices, model_parallelism=ep.model_parallelism
        )
        self.model = create_model(
            cfg.model_params.model_name,
            num_classes=cfg.dataset_params.num_classes,
            dataset_name=cfg.dataset_params.dataset_name,
            compute_dtype=self.compute_dtype,
            attention_impl=cfg.model_params.attention_impl,
            mesh=self.mesh,
        )
        self.loaders = loaders if loaders is not None else create_loaders(cfg)
        data_size = self.mesh.shape["data"]
        per_host_batch = cfg.dataset_params.total_batch_size // max(
            jax.process_count(), 1
        )
        if per_host_batch % (data_size // max(jax.process_count(), 1) or 1):
            raise ValueError(
                f"per-host batch {per_host_batch} not divisible by local "
                f"data-axis size — adjust total_batch_size or num_devices"
            )
        self.ckpts = ExperimentCheckpoints(self.expt_dir)
        # Identity stamps for the mid-level slot: a slot whose config hash
        # disagrees with the live config is never restored (it holds
        # mid-trajectory state trained under different knobs).
        self.config_hash = config_fingerprint(cfg)
        self.run_id = Path(self.expt_dir).name if self.expt_dir else ""
        self.metrics = MetricsLogger(self.expt_dir, self.prefix)
        self.wandb = WandbRun(cfg, self.prefix, self.expt_dir)

        self.steps_per_epoch = len(self.loaders.train_loader)
        if ep.max_steps_per_epoch:
            self.steps_per_epoch = min(self.steps_per_epoch, ep.max_steps_per_epoch)

        # Built per level (fresh optimizer semantics); cached by total_steps
        # so identical level budgets reuse one executable.
        self._step_cache: dict[int, tuple] = {}
        self.tx = None
        self.schedule = None

        if state is None:
            input_shape = (
                1,
                cfg.dataset_params.image_size,
                cfg.dataset_params.image_size,
                3,
            )
            # tx is rebuilt per level; init with a placeholder SGD so the
            # opt_state pytree has the final structure.
            tx0, _ = self._build_tx(epochs=ep.epochs_per_level)
            state = create_train_state(
                self.model,
                tx0,
                jax.random.PRNGKey(ep.seed),
                input_shape,
            )
            if cfg.model_params.pretrained_path:
                # Warm-start ViT weights from a local timm checkpoint
                # (reference deit.py:82-89; models/pretrained.py). Applied to
                # the fresh init only — resume/level restores keep their own
                # weights — and before the level-0 MODEL_INIT save, so the
                # imp rewind target carries the pretrained weights.
                from ..models.pretrained import load_pretrained

                state = state.replace(
                    params=load_pretrained(
                        cfg.model_params.pretrained_path, self.model, state.params
                    )
                )
        self.state = replicate(state, self.mesh)

        raw_eval = make_eval_step(self.model)
        self._eval_step = make_sharded_eval_step(raw_eval, self.mesh)
        self._scan_eval = make_sharded_scan_eval(make_scan_eval(raw_eval), self.mesh)
        self._eval_batches = None  # device-cached stacked test set
        # Opt-in compacted eval (experiment_params.compact_eval): compiled
        # eval steps cached by the compacted width signature — widths only
        # change when the masks do (once per level), so per-epoch evals
        # reuse one executable.
        self._plan_eval_cache: dict[tuple, Any] = {}
        self.last_compaction_report: Optional[dict] = None
        # Sparse-backend execution (experiment_params.compact_train and/or
        # nm_sparsity): at each level boundary ONE planner
        # (sparse/plan.py plan_execution) derives an ExecutionPlan from the
        # live masks — slice the whole train state onto a physically smaller
        # model where dead channels clear the savings threshold, gather the
        # surviving N:M-patterned contractions, and stay masked-dense where
        # neither pays. The per-plan step bundle is cached by
        # (total_steps, width signature, nm signature); _plan_ctx holds the
        # plan + the full-coordinate anchor (compaction only) while the
        # level runs on it (None <=> training masked-dense). Cache sizes and
        # the last plan report are exported on compact_metrics so the
        # bench/tests can read the shape the level ACTUALLY compiled.
        self._plan_step_cache: dict[tuple, tuple] = {}
        self._plan_ctx: Optional[dict] = None
        self.last_plan_report: Optional[dict] = None
        self.last_nm_report: Optional[dict] = None
        if ep.nm_sparsity:
            # Fail fast at harness construction: a contraction width that
            # does not divide into M-blocks would otherwise only surface at
            # the first prune step, a full level of training later.
            from ..config.schema import parse_nm
            from ..sparse.nm import check_divisibility

            _, m_block = parse_nm(ep.nm_sparsity)
            check_divisibility(self.state.masks, m_block)
        from ..serve.metrics import ServeMetrics

        self.compact_metrics = ServeMetrics()

    # ------------------------------------------------------------------ tx
    def _build_tx(self, epochs: int):
        op = self.cfg.optimizer_params
        schedule = create_schedule(
            op.scheduler_type,
            base_lr=op.lr,
            epochs=epochs,
            steps_per_epoch=self.steps_per_epoch,
            warmup_fraction=op.warmup_fraction,
        )
        tx = create_optimizer(
            op.optimizer_name,
            schedule,
            momentum=op.momentum,
            weight_decay=op.weight_decay,
        )
        return tx, schedule

    def setup_level(self, epochs: int) -> None:
        """Fresh optimizer + schedule for a level/cycle (reference
        _setup_optimizer/_setup_scheduler per level,
        standard_pruning_harness.py:174-175). Reuses the compiled step when
        the epoch budget (=> schedule constants) is unchanged."""
        total_steps = epochs * self.steps_per_epoch
        self._current_epochs = epochs  # compact path rebuilds the same tx
        if total_steps not in self._step_cache:
            tx, schedule = self._build_tx(epochs)
            raw_step = make_train_step(self.model, tx, schedule)
            step = make_sharded_train_step(raw_step, self.mesh)
            scan = make_sharded_scan_epoch(make_scan_epoch(raw_step), self.mesh)
            chunk = make_sharded_scan_chunk(make_scan_chunk(raw_step), self.mesh)
            self._step_cache[total_steps] = (tx, schedule, step, scan, chunk)
        self.tx, self.schedule, self._train_step, self._scan_epoch, self._scan_chunk = (
            self._step_cache[total_steps]
        )
        self.state = replicate(
            self.state.replace(
                step=jnp.zeros((), jnp.int32), opt_state=self.tx.init(self.state.params)
            ),
            self.mesh,
        )

    def maybe_rewind_optimizer(self, level: int) -> None:
        """WR + ``rewind_optimizer``: restore the momentum buffers captured
        at rewind_epoch (the reference's unrealized intent — dead
        reset_optimizer, harness_utils.py:24-46). The schedule still restarts
        from step 0 (per-level fresh scheduler, like the reference): the
        restored ScaleByScheduleState (schedule position captured at
        rewind_epoch) is swapped for the fresh level-start one so the
        schedule is not fast-forwarded. ONLY the schedule state is reset —
        e.g. AdamW's ScaleByAdamState.count drives bias correction for the
        restored moments and must come back with them."""
        import optax

        pp = self.cfg.pruning_params
        if level > 0 and pp.training_type == "wr" and pp.rewind_optimizer:
            fresh = self.state.opt_state
            opt = self.ckpts.load_optimizer(OPTIMIZER_REWIND, fresh)
            is_sched = lambda x: isinstance(x, optax.ScaleByScheduleState)
            opt = jax.tree.map(
                lambda r, f: f if is_sched(r) else r, opt, fresh, is_leaf=is_sched
            )
            self.state = replicate(self.state.replace(opt_state=opt), self.mesh)

    # --------------------------------------------------------------- loops
    def train_epoch(self) -> dict:
        """One pass over the train loader (reference train_epoch,
        base_harness.py:151-202). Returns host-side epoch means.

        Fast path: device-resident loaders expose ``epoch_arrays`` and the
        whole epoch runs as ONE lax.scan program (make_scan_epoch) — no
        per-step host dispatch at all. Streaming loaders (grain/tpk) take
        the chunked-scan path when ``dataset_params.scan_chunk_steps > 1``
        (K batches per compiled dispatch) and the per-batch path
        otherwise."""
        if (
            hasattr(self.loaders.train_loader, "epoch_arrays")
            and not self.cfg.experiment_params.max_steps_per_epoch
        ):
            t0 = time.perf_counter()
            batches = jax.device_put(
                self.loaders.train_loader.epoch_arrays(),
                epoch_sharding(self.mesh),
            )
            self.state, sums = self._scan_epoch(self.state, batches)
            sums = jax.device_get(sums)
            wall = time.perf_counter() - t0
            n = float(sums["count"])
            return {
                "train_loss": float(sums["loss_sum"]) / n,
                "train_acc": 100.0 * float(sums["correct"]) / n,
                "epoch_seconds": wall,
                "samples_per_sec": n / wall,
            }

        sums = None
        t0 = time.perf_counter()
        train_loader = self.loaders.train_loader
        train_scope = getattr(train_loader, "batch_scope", "global")
        chunk_steps = self.cfg.dataset_params.scan_chunk_steps
        if chunk_steps > 1 and hasattr(train_loader, "iter_chunks"):
            # Chunked-scan streamed path: the pipeline engine stacks K
            # prefetched batches ([K, B, ...]) and each full chunk runs as
            # ONE compiled lax.scan dispatch while the engine refills
            # behind it; a sub-K tail (epoch length % K) arrives as plain
            # per-step batches so only two executables ever compile.
            for batch in train_loader.iter_chunks(
                chunk_steps, max_batches=self.steps_per_epoch
            ):
                if batch[0].ndim == 5:
                    cb = assemble_chunk(batch, self.mesh, train_scope)
                    self.state, m = self._scan_chunk(self.state, cb)
                else:
                    b = assemble_batch(batch, self.mesh, train_scope)
                    self.state, m = self._train_step(self.state, b)
                    m = {k: v for k, v in m.items() if k != "lr"}
                sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
        else:
            for i, batch in enumerate(train_loader):
                if i >= self.steps_per_epoch:
                    break
                batch = assemble_batch(batch, self.mesh, train_scope)
                self.state, m = self._train_step(self.state, batch)
                m = {k: v for k, v in m.items() if k != "lr"}
                sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
        if sums is None:
            raise RuntimeError(
                "train loader yielded no batches — dataset smaller than "
                "total_batch_size with drop_last?"
            )
        sums = jax.device_get(sums)
        wall = time.perf_counter() - t0
        n = float(sums["count"])
        return {
            "train_loss": float(sums["loss_sum"]) / n,
            "train_acc": 100.0 * float(sums["correct"]) / n,
            "epoch_seconds": wall,
            "samples_per_sec": n / wall,
        }

    def evaluate(self) -> dict:
        """Full test pass (reference test, base_harness.py:204-245). For
        schedule-free optimizers this evaluates the averaged weights.

        With ``experiment_params.compact_eval`` the pass runs on the
        dead-channel-COMPACTED model instead (sparse/) — numerically
        equivalent up to fp reassociation, and the per-level size report
        lands on ``last_compaction_report``."""
        ev_state = self.state
        if self.cfg.optimizer_params.optimizer_name == "ScheduleFreeSGD":
            ev_state = ev_state.replace(
                params=eval_params(ev_state.opt_state, ev_state.params)
            )
        if self.cfg.experiment_params.compact_eval and self._plan_ctx is None:
            # With an ExecutionPlan live the state/step functions already run
            # the planned shape — compact: the state is small and _eval_step
            # is the small model's (re-compacting sliced params against the
            # full model's graph would be wrong); N:M: _eval_step already
            # runs the gathered reduced-width path. Either way that IS the
            # level's compact eval.
            return self._evaluate_compacted(ev_state)
        test_loader = self.loaders.test_loader
        if hasattr(test_loader, "eval_epoch_arrays"):
            # Device-resident eval: the padded stacked test set is cached in
            # HBM once and the whole pass runs as ONE lax.scan program —
            # matching the train scan path's zero-dispatch hot loop.
            if self._eval_batches is None:
                self._eval_batches = jax.device_put(
                    test_loader.eval_epoch_arrays(), epoch_sharding(self.mesh)
                )
            sums = jax.device_get(self._scan_eval(ev_state, self._eval_batches))
        else:
            sums = None
            test_scope = getattr(test_loader, "batch_scope", "global")
            for batch in test_loader:
                batch = assemble_batch(batch, self.mesh, test_scope)
                m = self._eval_step(ev_state, batch)
                sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
            if sums is None:
                raise RuntimeError("test loader yielded no batches")
            sums = jax.device_get(sums)
        n = float(sums["count"])
        return {
            "test_loss": float(sums["loss_sum"]) / n,
            "test_acc": 100.0 * float(sums["correct"]) / n,
        }

    def _evaluate_compacted(self, ev_state) -> dict:
        """Test pass on the physically compacted model (sparse/compact.py).

        The current state's masks are analyzed on the host, dead channels
        are sliced out, and the small model evaluates the same test set.
        Single-program (no mesh step): eval batches are replicated-small
        and the compacted executable is cached per width signature, so
        within a level every epoch reuses one compile. Ring attention falls
        back to its param-identical dense equivalent (as in serving)."""
        from ..sparse import build_graph, compact_params
        from ..train.state import TrainState

        graph = build_graph(self.model, ev_state.params)
        res = compact_params(
            ev_state.params, ev_state.masks, graph, ev_state.batch_stats
        )
        self.last_compaction_report = res.report
        key = res.as_override_tuple()
        if key not in self._plan_eval_cache:
            self._evict_stale_plan_caches(key)
            self._plan_eval_cache[key] = jax.jit(
                make_eval_step(self._small_model(res.width_overrides))
            )
            self._export_cache_gauges()
        step = self._plan_eval_cache[key]
        # make_eval_step multiplies masks into params; all-ones masks on
        # the compacted (already folded) params make that an exact no-op,
        # so the metric/padding semantics are shared with the dense path.
        small_state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=res.params,
            masks=masking.make_masks(res.params),
            batch_stats=res.batch_stats,
            opt_state=(),
            rng=jnp.zeros((), jnp.uint32),  # unused in eval
        )
        sums = None
        for batch in self.loaders.test_loader:
            m = step(small_state, batch)
            sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
        if sums is None:
            raise RuntimeError("test loader yielded no batches")
        sums = jax.device_get(sums)
        n = float(sums["count"])
        return {
            "test_loss": float(sums["loss_sum"]) / n,
            "test_acc": 100.0 * float(sums["correct"]) / n,
        }

    # ----------------------------------------------------- plan execution
    def _small_model(self, width_overrides, nm_overrides=None):
        """Re-instantiate the architecture at compacted widths and/or with
        gathered N:M hooks. Ring attention falls back to its param-identical
        dense equivalent (as in serving): the small model is replicated, not
        sequence-sharded."""
        attention_impl = self.cfg.model_params.attention_impl
        if attention_impl == "ring":
            attention_impl = "dense"
        return create_model(
            self.cfg.model_params.model_name,
            num_classes=self.cfg.dataset_params.num_classes,
            dataset_name=self.cfg.dataset_params.dataset_name,
            compute_dtype=self.compute_dtype,
            attention_impl=attention_impl,
            mesh=self.mesh,
            width_overrides=width_overrides,
            nm_overrides=nm_overrides,
        )

    def _enter_plan(self) -> None:
        """Derive this level's ExecutionPlan from the live masks and swap
        the step bundle onto it (sparse/plan.py plan_execution — the ONE
        producer of backend decisions).

        The planner decides everything the old compact-then-nm enter pair
        decided, in one place: slice the whole train state onto a
        physically smaller model when dead-channel savings clear
        ``planner.compact_min_savings``, gather the surviving N:M-patterned
        contractions, stay masked-dense where neither pays. When compaction
        commits, the FULL state at entry is kept as the anchor: at exit
        (and for any checkpoint written mid-level) the trained small state
        is scattered back over it, so removed coordinates — including
        consumer in-rows of dead channels, whose real magnitudes the next
        level's GLOBAL threshold must still see — come back exactly as the
        dense run would have left them (exact for weight_decay=0 with the
        per-level fresh optimizer; a removed coordinate then sees zero
        gradient and zero momentum, i.e. it never moves). The N:M half is a
        function swap at the planned shapes — no state transformation.

        The plan is a pure function of the replicated masks + model family
        (mask agreement across hosts is asserted once per level by
        driver.prune_level's exact check_state_equality), so every process
        derives the identical plan without a collective; when compact_train
        is enabled the width signature is still barriered below because
        committing changes which jittable program runs.
        """
        ep = self.cfg.experiment_params
        if self._plan_ctx is not None:
            return
        compact_mode = "auto" if ep.compact_train else "off"
        nm_mode = "auto" if ep.nm_sparsity else "off"
        if compact_mode == "off" and nm_mode == "off":
            return
        from ..sparse import plan_execution, width_signature

        pl = self.cfg.planner
        plan = plan_execution(
            self.model,
            self.state.params,
            self.state.masks,
            self.state.batch_stats,
            model_factory=self._small_model,
            compact=compact_mode,
            nm=nm_mode,
            compact_min_savings=pl.compact_min_savings,
            nm_min_axis_savings=pl.nm_min_axis_savings,
            autotune=pl.autotune,
        )
        if ep.compact_train:
            # Collective — every process must reach this call, with its
            # decision (including a planner decline or CompactionError)
            # encoded in the signature; skipping it on one host would
            # deadlock the others inside the allgather.
            if plan.compaction is not None:
                sig = {
                    "commit": True,
                    "widths": width_signature(plan.compaction),
                }
            else:
                sig = {
                    "commit": False,
                    "reason": plan.report["compaction"]["reason"],
                }
            assert_width_agreement(sig)
        self.last_plan_report = plan.report
        if plan.report["nm"] is not None:
            self.last_nm_report = plan.report["nm"]
        self.compact_metrics.record_plan(plan.report)
        if plan.kind == "masked":
            # Neither backend pays at this level: keep the dense bundle.
            return
        if plan.compaction is not None:
            self.last_compaction_report = plan.compaction.report

        total_steps = self._current_epochs * self.steps_per_epoch
        width_key, nm_key = plan.width_key(), plan.nm_key()
        key = (total_steps, width_key, nm_key)
        self._evict_stale_plan_caches(width_key, nm_key)
        if key not in self._plan_step_cache:
            exec_model = self._small_model(
                plan.width_overrides, nm_overrides=plan.nm_overrides
            )
            tx, schedule = self._build_tx(self._current_epochs)
            raw_step = make_train_step(exec_model, tx, schedule)
            raw_eval = make_eval_step(exec_model)
            self._plan_step_cache[key] = (
                make_sharded_train_step(raw_step, self.mesh),
                make_sharded_scan_epoch(make_scan_epoch(raw_step), self.mesh),
                make_sharded_scan_chunk(make_scan_chunk(raw_step), self.mesh),
                make_sharded_eval_step(raw_eval, self.mesh),
                make_sharded_scan_eval(make_scan_eval(raw_eval), self.mesh),
            )
        self._export_cache_gauges()
        self._plan_ctx = {
            "plan": plan,
            "anchor": self.state if plan.compaction is not None else None,
            "dense_fns": (
                self._train_step,
                self._scan_epoch,
                self._scan_chunk,
                self._eval_step,
                self._scan_eval,
            ),
        }
        (
            self._train_step,
            self._scan_epoch,
            self._scan_chunk,
            self._eval_step,
            self._scan_eval,
        ) = self._plan_step_cache[key]
        if plan.compaction is not None:
            from ..sparse import compact_train_state

            self.state = replicate(
                compact_train_state(self.state, plan.compaction), self.mesh
            )
        if is_primary():
            r = plan.report
            parts = []
            comp = r["compaction"]
            if plan.compaction is not None:
                parts.append(
                    f"params {comp['params_before']:,} -> "
                    f"{comp['params_after']:,} "
                    f"({r['backend_counts']['compact_spaces']} spaces)"
                )
            if plan.nm is not None:
                parts.append(
                    f"{r['backend_counts']['nm_layers']} layers gathered "
                    f"(coverage {r['coverage_frac']:.2f})"
                )
            print(
                f"[plan] level runs {plan.kind}: " + ", ".join(parts),
                flush=True,
            )

    def _exit_plan(self) -> None:
        """Expand back to full coordinates (when the plan compacted) and
        restore the masked-dense step functions. Idempotent; called in a
        finally so a raising epoch can't leave the harness stuck on a
        plan's shapes (the driver's save_level/prune always see full
        coordinates)."""
        if self._plan_ctx is None:
            return
        ctx = self._plan_ctx
        self._plan_ctx = None
        (
            self._train_step,
            self._scan_epoch,
            self._scan_chunk,
            self._eval_step,
            self._scan_eval,
        ) = ctx["dense_fns"]
        plan = ctx["plan"]
        if plan.compaction is not None:
            from ..sparse import expand_train_state

            self.state = replicate(
                expand_train_state(
                    self.state, plan.compaction, anchor=ctx["anchor"]
                ),
                self.mesh,
            )

    def _full_state(self) -> TrainState:
        """The live state in FULL coordinates — what every checkpoint
        (rewind artifacts, mid-level slots) must hold so restores never
        learn the level ran small."""
        ctx = self._plan_ctx
        if ctx is None or ctx["plan"].compaction is None:
            return self.state
        from ..sparse import expand_train_state

        return expand_train_state(
            self.state, ctx["plan"].compaction, anchor=ctx["anchor"]
        )

    def _full_masks(self):
        """Full-coordinate masks for metric rows. Masks never change inside
        a level, so while compacted the anchor's tree IS the current one."""
        ctx = self._plan_ctx
        if ctx is None or ctx["plan"].compaction is None:
            return self.state.masks
        return ctx["anchor"].masks

    def _evict_stale_plan_caches(
        self, width_key: tuple, nm_key: Optional[tuple] = None
    ) -> None:
        """The ladder only descends — executables compiled for an older
        (wider, or differently-indexed) plan signature can never be hit
        again and would pin dead HLO + donated buffers for the rest of the
        run. ``nm_key=None`` (the compact-eval path) evicts on widths
        only."""
        for k in [
            k
            for k in self._plan_step_cache
            if k[1] != width_key or (nm_key is not None and k[2] != nm_key)
        ]:
            del self._plan_step_cache[k]
        for k in [k for k in self._plan_eval_cache if k != width_key]:
            del self._plan_eval_cache[k]

    def _export_cache_gauges(self) -> None:
        self.compact_metrics.set_gauge(
            "plan_step_cache_size", len(self._plan_step_cache)
        )
        self.compact_metrics.set_gauge(
            "plan_eval_cache_size", len(self._plan_eval_cache)
        )

    # --------------------------------------------------------------- level
    def train_one_level(self, epochs_per_level: int, level: int) -> dict:
        """Train one sparsity level (reference train_one_level,
        standard_pruning_harness.py:159-269)."""
        self.setup_level(epochs_per_level)
        self.maybe_rewind_optimizer(level)
        density = masking.overall_density(self.state.masks)
        display_training_info(self.cfg, level, density)

        if level == 0:
            # Level-0 artifacts: starting weights + optimizer (imp rewind
            # target; standard_pruning_harness.py:190-199).
            self.ckpts.save_model(MODEL_INIT, self.state)
            self.ckpts.save_optimizer(OPTIMIZER_INIT, self.state.opt_state)

        rewind_epoch = self.cfg.pruning_params.rewind_epoch
        profile_dir = self.cfg.experiment_params.profile_dir
        ckpt_every = self.cfg.experiment_params.checkpoint_every_epochs
        max_test_acc = 0.0
        start_epoch = 0
        mid = self.ckpts.peek_mid_level() if ckpt_every else None
        if mid and mid.get("config_hash") != self.config_hash:
            # Identity mismatch (or a pre-stamp slot of unknown provenance):
            # the slot holds mid-trajectory state trained under a DIFFERENT
            # config (lr, epoch budget, loader type, ...) — restoring it
            # would silently continue the wrong trajectory. Refuse and
            # replay the level from its start.
            if is_primary():
                print(
                    "[resume] REFUSING mid-level restore: slot config hash "
                    f"{mid.get('config_hash')!r} != current "
                    f"{self.config_hash!r} (run {mid.get('run_id')!r}) — "
                    "the config changed since the slot was written; "
                    "replaying the level from its start",
                    flush=True,
                )
            self.ckpts.clear_mid_level()
        elif mid and mid["level"] != level:
            # Levels run in ascending order, so a slot for a different level
            # is always from an abandoned trajectory (e.g. resumed BELOW a
            # preempted level) — drop it before it can hijack a later
            # re-run of its level.
            self.ckpts.clear_mid_level()
        elif mid:
            # Epoch-granular re-entry (beyond-reference; checkpoint.py
            # MID_LEVEL): restore the FULL state — opt_state and step come
            # back mid-schedule — and fast-forward the train loader's epoch
            # counter so the per-epoch shuffle/augment PRNG stream continues
            # exactly where the interrupted run left it (bit-identical to an
            # uninterrupted run; asserted in tests/test_harness.py).
            restored = self.ckpts.load_mid_level(
                self.state, expect_level=level, expect_epoch=mid["epoch"]
            )
            if restored is None:
                # Torn save (header and state tree from different saves):
                # replay the level from its start instead of mixing them.
                if is_primary():
                    print(
                        "[resume] mid-level slot is torn (header/state "
                        "disagree) — replaying the level",
                        flush=True,
                    )
                self.ckpts.clear_mid_level()
            else:
                self.state = replicate(
                    self.state.replace(**restored), self.mesh
                )
                start_epoch = mid["epoch"] + 1
                max_test_acc = mid.get("max_test_acc", 0.0)
                # Pre-preemption epoch rows ride in the header so the level
                # CSV and the summary's max_test_acc cover the WHOLE level,
                # not just the post-resume epochs.
                self.metrics.level_rows = [
                    dict(r) for r in mid.get("level_rows", [])
                ]
                self._restore_train_stream(mid, level)
                if is_primary():
                    print(
                        f"[resume] mid-level checkpoint: re-entering level "
                        f"{level} at epoch {start_epoch}",
                        flush=True,
                    )
        # After any mid-level restore, so the anchor is the true level-start
        # full state (post-rewind, post-resume) and a resumed level
        # re-derives its ExecutionPlan from the restored full coordinates.
        self._enter_plan()
        try:
            for epoch in range(start_epoch, epochs_per_level):
                # Trace the second epoch of level 0 (first is
                # compile-polluted).
                tracing = bool(profile_dir) and level == 0 and epoch == 1
                if tracing:
                    jax.profiler.start_trace(profile_dir)
                row = {"level": level, "epoch": epoch}
                row.update(self.train_epoch())
                if tracing:
                    jax.profiler.stop_trace()
                row.update(self.evaluate())
                max_test_acc = max(max_test_acc, row["test_acc"])
                row["max_test_acc"] = max_test_acc
                row["sparsity"] = masking.overall_sparsity(self._full_masks())
                self.metrics.log_epoch(row)
                self.wandb.log(row)
                self._log_console(row)

                if level == 0 and rewind_epoch is not None and epoch == rewind_epoch:
                    # Weight-rewinding snapshot (standard_pruning_harness.py:
                    # 212-223). Full coordinates — the rewind target must
                    # not depend on whether this level ran compacted.
                    full = self._full_state()
                    self.ckpts.save_model(MODEL_REWIND, full)
                    self.ckpts.save_optimizer(OPTIMIZER_REWIND, full.opt_state)

                if (
                    ckpt_every
                    and (epoch + 1) % ckpt_every == 0
                    and epoch + 1 < epochs_per_level  # last epoch -> level ckpt
                ):
                    meta = {
                        "max_test_acc": max_test_acc,
                        # Slot identity (ADVICE r5): the restore path refuses
                        # a slot whose config hash disagrees with the live
                        # run.
                        "config_hash": self.config_hash,
                        "run_id": self.run_id,
                        "train_loader_epoch": getattr(
                            self.loaders.train_loader, "epoch", 0
                        ),
                        # So the level CSV / summary survive the preemption
                        # (rows are plain float/int dicts — JSON-safe).
                        "level_rows": self.metrics.level_rows,
                    }
                    get_stream = getattr(
                        self.loaders.train_loader, "get_stream_state", None
                    )
                    if get_stream is not None:
                        stream = get_stream()
                        if stream is not None:
                            # EVERY host writes its own blob (its own shard
                            # position) — a shared primary-only header would
                            # hand all hosts the primary's position.
                            self.ckpts.save_mid_level_stream(
                                level, epoch, stream, jax.process_index()
                            )
                            meta["train_loader_stream_hosts"] = (
                                jax.process_count()
                            )
                    self.ckpts.save_mid_level(
                        level, epoch, self._full_state(), meta=meta
                    )
        finally:
            self._exit_plan()

        return self.metrics.finish_level(
            level,
            {
                "density": density,
                "final_sparsity": masking.overall_sparsity(self.state.masks),
            },
        )

    def _restore_train_stream(self, mid: dict, level: int) -> None:
        """Restore the train loader's data-order state on mid-level resume.

        Three tiers, degrading gracefully (never crashing the resume):
        1. Stream-position loaders (grain): per-host tagged blob written by
           save_mid_level_stream — each host restores ITS OWN shard
           position. Missing/mistagged blob, changed host count, or a
           loader that rejects the state (e.g. num_workers changed) falls
           through to tier 3 with a warning.
        2. (seed, epoch)-stateless loaders (device/tpk/synthetic): the
           epoch counter IS the state; restoring it is bit-exact.
        3. Fallback: fresh shuffle pass — statistically equivalent, loudly
           not bit-identical."""
        train_loader = self.loaders.train_loader
        epoch = mid["train_loader_epoch"]
        if mid.get("train_loader_stream_hosts") and hasattr(
            train_loader, "set_stream_state"
        ):
            blob = None
            if mid["train_loader_stream_hosts"] == jax.process_count():
                blob = self.ckpts.load_mid_level_stream(
                    level, mid["epoch"], jax.process_index()
                )
            if blob is not None:
                try:
                    train_loader.set_stream_state(blob)
                    if hasattr(train_loader, "epoch"):
                        train_loader.epoch = epoch
                    return
                except Exception as e:  # incompatible state: degrade, don't die
                    if is_primary():
                        print(
                            f"[resume] stream state rejected ({e!r:.200}); "
                            "falling back to a fresh shuffle pass",
                            flush=True,
                        )
            elif is_primary():
                print(
                    "[resume] stream-state blob missing or from a different "
                    "save/host-count; falling back to a fresh shuffle pass",
                    flush=True,
                )
        elif getattr(train_loader, "resumable_epochs", True) and hasattr(
            train_loader, "epoch"
        ):
            train_loader.epoch = epoch
            return
        if is_primary():
            print(
                "[resume] WARNING: the resumed run sees a fresh shuffle "
                "pass — statistically equivalent, NOT bit-identical to an "
                "uninterrupted run",
                flush=True,
            )

    def _log_console(self, row: dict) -> None:
        print(
            f"[L{row['level']:>2} E{row['epoch']:>3}] "
            f"train {row['train_loss']:.4f}/{row['train_acc']:5.2f}% "
            f"test {row['test_loss']:.4f}/{row['test_acc']:5.2f}% "
            f"(best {row['max_test_acc']:5.2f}%) "
            f"sparsity {row['sparsity']:5.2f}% "
            f"{row['samples_per_sec']:,.0f} img/s",
            flush=True,
        )
