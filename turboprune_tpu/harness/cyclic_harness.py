"""CyclicPruningHarness — repeated LR re-warming within a sparsity level.

Reference: /root/reference/harness_definitions/cyclic_harness.py:25-299 —
identical to the standard harness except ``train_one_level`` splits the
epoch budget across ``num_cycles`` cycles (8 split strategies,
harness_utils.py:159-245) and re-creates optimizer + schedule each cycle
(cyclic_harness.py:193-194), logging a ``cycle`` column. The reference's
call into its schedule generator is broken for num_cycles>1
(cyclic_harness.py:175 passes kwargs the function doesn't take — SURVEY.md
§2.1); here the signature actually matches.
"""

from __future__ import annotations

from ..config.schema import ConfigError
from ..ops import masking
from ..pruning import generate_cyclical_schedule
from ..utils import MODEL_INIT, MODEL_REWIND, OPTIMIZER_INIT, OPTIMIZER_REWIND
from ..utils.experiment import display_training_info
from .pruning_harness import PruningHarness


class CyclicPruningHarness(PruningHarness):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.cfg.experiment_params.checkpoint_every_epochs:
            # The cyclic level loop below fully overrides the base harness's
            # and has no mid-level re-entry: accepting the knob would
            # silently provide NO preemption protection.
            raise ConfigError(
                "experiment_params.checkpoint_every_epochs > 0 is not "
                "supported with cyclic training — the cyclic loop cannot "
                "resume mid-level, so the setting would be a silent no-op. "
                "Set checkpoint_every_epochs=0 (level-granular resume still "
                "works)."
            )

    def train_one_level(
        self, epochs_per_level: int, level: int, num_cycles: int = 0
    ) -> dict:
        ct = self.cfg.cyclic_training
        num_cycles = num_cycles or ct.num_cycles
        cycle_epochs = generate_cyclical_schedule(
            epochs_per_level, num_cycles, ct.strategy
        )
        density = masking.overall_density(self.state.masks)
        display_training_info(self.cfg, level, density)

        if level == 0:
            # Save BEFORE any training so cycle-0 state is the true init
            # (reference saves inside the first cycle, cyclic_harness.py:
            # 202-211; we need a fresh opt_state pytree for the artifact).
            self.setup_level(cycle_epochs[0])
            self.ckpts.save_model(MODEL_INIT, self.state)
            self.ckpts.save_optimizer(OPTIMIZER_INIT, self.state.opt_state)

        rewind_epoch = self.cfg.pruning_params.rewind_epoch
        max_test_acc = 0.0
        for cycle, epochs in enumerate(cycle_epochs):
            # Fresh optimizer + schedule per cycle: the LR re-warms from the
            # schedule's start (cyclic_harness.py:180-194). setup_level
            # re-inits the optimizer from FULL params, so the execution plan
            # enters/exits per cycle — the planned step bundle is cached by
            # (total_steps, widths, nm signature) and cycles with equal
            # epoch budgets reuse one executable.
            self.setup_level(epochs)
            if cycle == 0:
                self.maybe_rewind_optimizer(level)
            self._enter_plan()
            try:
                for epoch in range(epochs):
                    row = {"level": level, "cycle": cycle, "epoch": epoch}
                    row.update(self.train_epoch())
                    row.update(self.evaluate())
                    max_test_acc = max(max_test_acc, row["test_acc"])
                    row["max_test_acc"] = max_test_acc
                    row["sparsity"] = masking.overall_sparsity(
                        self._full_masks()
                    )
                    self.metrics.log_epoch(row)
                    self.wandb.log(row)
                    self._log_console(row)

                    if (
                        level == 0
                        and cycle == 0
                        and rewind_epoch is not None
                        and epoch == rewind_epoch
                    ):
                        full = self._full_state()
                        self.ckpts.save_model(MODEL_REWIND, full)
                        self.ckpts.save_optimizer(
                            OPTIMIZER_REWIND, full.opt_state
                        )
            finally:
                self._exit_plan()

        return self.metrics.finish_level(
            level,
            {
                "density": density,
                "final_sparsity": masking.overall_sparsity(self.state.masks),
                "num_cycles": num_cycles,
            },
        )

    def _log_console(self, row: dict) -> None:
        cyc = row.get("cycle", 0)
        print(
            f"[L{row['level']:>2} C{cyc} E{row['epoch']:>3}] "
            f"train {row['train_loss']:.4f}/{row['train_acc']:5.2f}% "
            f"test {row['test_loss']:.4f}/{row['test_acc']:5.2f}% "
            f"sparsity {row['sparsity']:5.2f}%",
            flush=True,
        )
