"""Dtype lattice + abstract interpretation for the dtype-flow rules.

JAX's promotion table makes reduced precision easy to lose silently: a
strongly-typed ``np.float32`` scalar, a default-dtype ``jnp.mean``, or one
``jnp.zeros`` without ``dtype=`` quietly promotes a bf16 path back to
f32 — no error, no speedup, and the jaxpr is the only witness. This module
gives the rules in dtype_rules.py a static approximation of that table:

* a small dtype lattice — ``f64 / f32 / bf16 / f16 / int / weak-float /
  weak-int / unknown`` — with :func:`join` modelling JAX's binary-op
  promotion (weak scalars promote DOWN into strong dtypes; two strong
  floats promote UP to the wider one; ``unknown`` absorbs);
* :class:`ScopeDtypes`, a single-pass abstract interpreter over a function
  body that assigns every expression node a lattice value (assignments
  flow, branches join, loop bodies run twice for loop-carried names);
* dtype-policy comments — ``# graftlint: dtype-policy=bf16`` — parsed like
  waivers (tokenizer, so ``#`` in strings is ignored). A policy comment
  applies to the next function definition below it; with no def following
  it declares the whole module. Policies both OPT IN (``bf16`` seeds the
  region's traced params reduced so the upcast rules fire) and OPT OUT
  (``fp32`` on a region with incidental bf16 markers silences them).

Everything here is stdlib ``ast``/``tokenize`` — same no-jax-at-import
contract as the rest of the package. The promotion model is deliberately
an approximation: ``unknown`` is the honest default, and rules only fire
when BOTH sides of a hazard infer to known lattice values, so precision
errs toward silence, never toward false findings.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Iterable, Optional

from .regions import dotted_name

__all__ = [
    "UNKNOWN",
    "REDUCED",
    "STRONG_FLOATS",
    "join",
    "binop_result",
    "dtype_from_expr",
    "ScopeDtypes",
    "DtypePolicies",
    "parse_dtype_policies",
    "reduced_hint",
    "region_reduced",
]

# ------------------------------------------------------------------ lattice

F64, F32, BF16, F16 = "f64", "f32", "bf16", "f16"
INT = "int"
WEAK_FLOAT, WEAK_INT = "weak-float", "weak-int"
UNKNOWN = "unknown"

REDUCED = frozenset({BF16, F16})
STRONG_FLOATS = frozenset({F64, F32, BF16, F16})
WEAK = frozenset({WEAK_FLOAT, WEAK_INT})

_FLOAT_RANK = {BF16: 1, F16: 1, F32: 2, F64: 3}


def join(a: str, b: str) -> str:
    """Result dtype of a binary op between ``a`` and ``b`` under JAX's
    promotion rules (the interesting property: weak scalars promote DOWN —
    ``bf16 + 1.0`` stays bf16 — while strong operands promote UP —
    ``bf16 + np.float32(1)`` is f32)."""
    if a == b:
        return a
    if a == UNKNOWN or b == UNKNOWN:
        return UNKNOWN
    if a in WEAK and b in WEAK:
        return WEAK_FLOAT if WEAK_FLOAT in (a, b) else WEAK_INT
    # one weak, one strong: weak-int never promotes; weak-float promotes an
    # INT operand to the default float type and leaves floats alone.
    for weak, strong in ((a, b), (b, a)):
        if weak in WEAK:
            if weak == WEAK_FLOAT and strong == INT:
                return F32
            return strong
    # both strong
    if a == INT:
        return b
    if b == INT:
        return a
    if _FLOAT_RANK[a] == _FLOAT_RANK[b]:
        return F32  # bf16 + f16 -> f32 in JAX's table
    return a if _FLOAT_RANK[a] > _FLOAT_RANK[b] else b


def binop_result(op: ast.AST, a: str, b: str) -> str:
    """``join`` plus true-division's int -> float coercion."""
    out = join(a, b)
    if isinstance(op, ast.Div) and out in (INT, WEAK_INT):
        return WEAK_FLOAT if out == WEAK_INT else F32
    return out


# ------------------------------------------------- dtype-name recognition

_DTYPE_TAILS = {
    "bfloat16": BF16,
    "float16": F16,
    "half": F16,
    "float32": F32,
    "single": F32,
    "float64": F64,
    "double": F64,
    "float_": F64,
    "int8": INT,
    "int16": INT,
    "int32": INT,
    "int64": INT,
    "uint8": INT,
    "uint16": INT,
    "uint32": INT,
    "uint64": INT,
    "int_": INT,
    "bool_": INT,
}
_DTYPE_ROOTS = {"jnp", "np", "numpy", "onp", "jax", "ml_dtypes"}


def _dtype_from_name(name: Optional[str]) -> Optional[str]:
    if not name:
        return None
    parts = name.split(".")
    if parts[-1] not in _DTYPE_TAILS:
        return None
    if len(parts) > 1 and parts[0] not in _DTYPE_ROOTS:
        return None
    return _DTYPE_TAILS[parts[-1]]


def dtype_from_expr(node: Optional[ast.AST]) -> Optional[str]:
    """``jnp.bfloat16`` / ``np.float32`` / ``"bfloat16"`` -> lattice value;
    None for anything unrecognized (a variable holding a dtype, etc.)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_TAILS.get(node.value)
    return _dtype_from_name(dotted_name(node))


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


# ------------------------------------------------- the abstract interpreter

def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _root(name: Optional[str]) -> Optional[str]:
    return name.split(".", 1)[0] if name else None


def _is_jnp(name: Optional[str]) -> bool:
    if not name:
        return False
    return (
        _root(name) in ("jnp", "nn")
        or name.startswith("jax.numpy.")
        or name.startswith("jax.nn.")
        or name.startswith("jax.scipy.")
    )


def _is_np(name: Optional[str]) -> bool:
    return _root(name) in ("np", "numpy", "onp")


def _is_lax(name: Optional[str]) -> bool:
    return bool(name) and "lax" in name.split(".")


_CREATION = {"zeros", "ones", "empty", "full", "eye", "identity", "arange", "linspace"}
_LIKE = {"zeros_like", "ones_like", "empty_like", "full_like"}
_CONVERT = {"array", "asarray"}
_REDUCTIONS = {
    "sum", "mean", "prod", "var", "std", "amax", "amin", "max", "min",
    "nansum", "nanmean", "cumsum", "cumprod", "average", "norm", "logsumexp",
}
_MATMULS = {"matmul", "dot", "tensordot", "inner", "outer", "vdot", "einsum"}
_INT_RESULTS = {"argmax", "argmin", "argsort", "searchsorted", "digitize"}
_PAIR_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "true_divide", "power",
    "maximum", "minimum", "mod", "remainder", "atan2", "hypot",
}
_PASSTHROUGH = {
    "exp", "log", "log2", "log10", "log1p", "expm1", "sqrt", "rsqrt",
    "tanh", "sin", "cos", "tan", "sinh", "cosh", "erf", "abs", "negative",
    "square", "sign", "relu", "relu6", "gelu", "silu", "swish", "sigmoid",
    "softplus", "softmax", "log_softmax", "logsumexp", "reshape",
    "transpose", "broadcast_to", "squeeze", "expand_dims", "ravel", "roll",
    "flip", "pad", "tile", "repeat", "sort", "clip", "take",
    "take_along_axis", "moveaxis", "swapaxes", "real", "stop_gradient",
    "cumsum", "cumprod", "tril", "triu", "diag", "nan_to_num",
}
_JOIN_LIST = {"concatenate", "stack", "hstack", "vstack", "block"}
_SELF_METHODS_PASS = {
    "reshape", "transpose", "copy", "flatten", "ravel", "squeeze", "clip",
    "take", "sort", "round", "conj", "block_until_ready",
}
_SELF_METHODS_REDUCE = {"sum", "mean", "prod", "max", "min", "var", "std", "cumsum"}
_RANDOM_SAMPLERS = {
    "normal", "uniform", "truncated_normal", "gamma", "beta", "exponential",
    "laplace", "cauchy", "dirichlet", "ball", "gumbel", "logistic",
}


class ScopeDtypes:
    """One forward pass over a function (or module) body: every expression
    node gets a lattice value in ``self.at`` (keyed by ``id(node)``), and
    top-level ``return`` statements collect in ``self.returns``.

    Nested function definitions are interpreted with a copy of the current
    environment (closures see outer bindings) and their parameters seeded
    unknown — their expression dtypes land in ``self.at`` too, but their
    assignments don't leak out and their returns aren't the scope's.
    """

    def __init__(self, scope: Optional[ast.AST], seed: Optional[dict] = None):
        self.at: dict = {}
        self.returns: list = []  # (Return node, dtype-of-value)
        env = dict(seed or {})
        if scope is None:
            return
        if isinstance(scope, ast.Module):
            self._exec_block(scope.body, env, top=True)
        elif isinstance(scope, ast.Lambda):
            d = self._infer(scope.body, env)
            self.returns.append((scope.body, d))
        else:  # FunctionDef / AsyncFunctionDef
            for p in self._params(scope):
                env.setdefault(p, UNKNOWN)
            self._exec_block(scope.body, env, top=True)

    # ---------------------------------------------------------------- query

    def dtype_of(self, node: ast.AST) -> str:
        return self.at.get(id(node), UNKNOWN)

    # -------------------------------------------------------------- helpers

    @staticmethod
    def _params(fn: ast.AST) -> list:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def _assign_target(self, target: ast.AST, dtype: str, env: dict) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = dtype
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, UNKNOWN, env)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(elt, UNKNOWN, env)
        # attribute/subscript targets: no tracked binding

    def _assign(self, target: ast.AST, value: ast.AST, env: dict) -> None:
        if isinstance(target, (ast.Tuple, ast.List)) and isinstance(
            value, (ast.Tuple, ast.List)
        ) and len(target.elts) == len(value.elts):
            for t, v in zip(target.elts, value.elts):
                self._assign(t, v, env)
            return
        self._assign_target(target, self._infer(value, env), env)

    # ----------------------------------------------------------- statements

    def _exec_block(self, stmts: Iterable, env: dict, top: bool) -> None:
        for stmt in stmts:
            self._exec(stmt, env, top)

    def _exec(self, stmt: ast.AST, env: dict, top: bool) -> None:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._assign(t, stmt.value, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, stmt.value, env)
        elif isinstance(stmt, ast.AugAssign):
            v = self._infer(stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                cur = env.get(stmt.target.id, UNKNOWN)
                env[stmt.target.id] = binop_result(stmt.op, cur, v)
        elif isinstance(stmt, ast.Return):
            d = self._infer(stmt.value, env) if stmt.value is not None else UNKNOWN
            if top:
                self.returns.append((stmt, d))
        elif isinstance(stmt, ast.Expr):
            self._infer(stmt.value, env)
        elif isinstance(stmt, ast.If):
            self._infer(stmt.test, env)
            a, b = dict(env), dict(env)
            self._exec_block(stmt.body, a, top)
            self._exec_block(stmt.orelse, b, top)
            for k in set(a) | set(b):
                env[k] = join(a.get(k, UNKNOWN), b.get(k, UNKNOWN))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._infer(stmt.iter, env)
            self._assign_target(stmt.target, UNKNOWN, env)
            # two passes so loop-carried rebindings converge
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.orelse, env, top)
        elif isinstance(stmt, ast.While):
            self._infer(stmt.test, env)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.orelse, env, top)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._infer(item.context_expr, env)
            self._exec_block(stmt.body, env, top)
        elif isinstance(stmt, ast.Try):
            self._exec_block(stmt.body, env, top)
            for h in stmt.handlers:
                self._exec_block(h.body, env, top)
            self._exec_block(stmt.orelse, env, top)
            self._exec_block(stmt.finalbody, env, top)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = dict(env)
            for p in self._params(stmt):
                inner[p] = UNKNOWN
            self._exec_block(stmt.body, inner, top=False)
        # ClassDef / imports / pass / etc: nothing to track

    # ---------------------------------------------------------- expressions

    def _infer(self, node: Optional[ast.AST], env: dict) -> str:
        if node is None:
            return UNKNOWN
        d = self._infer_inner(node, env)
        self.at[id(node)] = d
        return d

    def _infer_inner(self, node: ast.AST, env: dict) -> str:
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool):
                return WEAK_INT
            if isinstance(v, int):
                return WEAK_INT
            if isinstance(v, float):
                return WEAK_FLOAT
            return UNKNOWN
        if isinstance(node, ast.Name):
            return env.get(node.id, UNKNOWN)
        if isinstance(node, ast.BinOp):
            return binop_result(
                node.op,
                self._infer(node.left, env),
                self._infer(node.right, env),
            )
        if isinstance(node, ast.UnaryOp):
            return self._infer(node.operand, env)
        if isinstance(node, ast.IfExp):
            self._infer(node.test, env)
            return join(
                self._infer(node.body, env), self._infer(node.orelse, env)
            )
        if isinstance(node, ast.Compare):
            self._infer(node.left, env)
            for c in node.comparators:
                self._infer(c, env)
            return INT  # bool array; behaves as an integer type in promotion
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._infer(v, env)
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            self._infer(node.slice, env)
            return self._infer(node.value, env)
        if isinstance(node, ast.Attribute):
            if node.attr in ("T", "mT", "real", "at"):
                return self._infer(node.value, env)
            if node.attr in ("ndim", "size"):
                self._infer(node.value, env)
                return WEAK_INT
            self._infer(node.value, env)
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for elt in node.elts:
                self._infer(elt, env)
            return UNKNOWN
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                self._infer(k, env)
                self._infer(v, env)
            return UNKNOWN
        if isinstance(node, ast.Call):
            return self._infer_call(node, env)
        if isinstance(node, ast.Lambda):
            inner = dict(env)
            for p in self._params(node):
                inner[p] = UNKNOWN
            self._infer(node.body, inner)
            return UNKNOWN
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            return UNKNOWN
        if isinstance(node, ast.Starred):
            return self._infer(node.value, env)
        return UNKNOWN

    def _infer_call(self, node: ast.Call, env: dict) -> str:
        for arg in node.args:
            self._infer(arg, env)
        for kw in node.keywords:
            self._infer(kw.value, env)

        f = node.func
        # --- method calls ------------------------------------------------
        if isinstance(f, ast.Attribute):
            recv = self._infer(f.value, env)
            if f.attr == "astype":
                return dtype_from_expr(node.args[0] if node.args else _kw(node, "dtype")) or UNKNOWN
            if f.attr in _SELF_METHODS_REDUCE:
                d = dtype_from_expr(_kw(node, "dtype"))
                return d if d else recv
            if f.attr in _SELF_METHODS_PASS:
                return recv
            if f.attr in ("set", "add", "multiply", "divide", "min", "max", "power", "get", "apply"):
                # .at[idx].set(v) family: result keeps the array's dtype
                if _chain_has_at(f.value):
                    return recv
        name = dotted_name(f)
        tail = _tail(name)

        # --- dtype constructors: jnp.float32(x), np.bfloat16(x), ... ------
        ctor = _dtype_from_name(name)
        if ctor and isinstance(f, (ast.Name, ast.Attribute)):
            return ctor
        if name == "float":
            return WEAK_FLOAT
        if name in ("int", "len", "round", "ord"):
            return WEAK_INT

        if name is None or tail is None:
            return UNKNOWN

        explicit = dtype_from_expr(_kw(node, "dtype"))
        pet = dtype_from_expr(_kw(node, "preferred_element_type"))

        # --- jax.numpy / jax.nn -------------------------------------------
        if _is_jnp(name) or _is_lax(name):
            if tail == "astype" and len(node.args) >= 2:
                return dtype_from_expr(node.args[1]) or UNKNOWN
            if tail == "convert_element_type":
                d = dtype_from_expr(_kw(node, "new_dtype")) or dtype_from_expr(
                    node.args[1] if len(node.args) >= 2 else None
                )
                return d or UNKNOWN
            if tail in ("dot_general", "conv_general_dilated", "conv"):
                if pet:
                    return pet
                if len(node.args) >= 2:
                    return join(
                        self.dtype_of(node.args[0]), self.dtype_of(node.args[1])
                    )
                return UNKNOWN
            if tail in _MATMULS:
                if pet:
                    return pet
                operands = node.args
                if tail == "einsum" and operands and isinstance(operands[0], ast.Constant):
                    operands = operands[1:]
                out = UNKNOWN
                known = [
                    self.dtype_of(a) for a in operands
                    if self.dtype_of(a) != UNKNOWN
                ]
                if known and len(known) == len(list(operands)):
                    out = known[0]
                    for d in known[1:]:
                        out = join(out, d)
                return out
            if tail in _CREATION:
                if explicit:
                    return explicit
                if tail == "full" and len(node.args) >= 3:
                    d = dtype_from_expr(node.args[2])
                    if d:
                        return d
                if tail == "arange":
                    if all(self.dtype_of(a) in (WEAK_INT, INT) for a in node.args):
                        return INT
                return F32
            if tail in _CONVERT:
                if explicit:
                    return explicit
                if len(node.args) >= 2:
                    d = dtype_from_expr(node.args[1])
                    if d:
                        return d
                return self.dtype_of(node.args[0]) if node.args else UNKNOWN
            if tail in _LIKE:
                if explicit:
                    return explicit
                return self.dtype_of(node.args[0]) if node.args else UNKNOWN
            if tail in _REDUCTIONS:
                if explicit:
                    return explicit
                return self.dtype_of(node.args[0]) if node.args else UNKNOWN
            if tail in _INT_RESULTS:
                return INT
            if tail == "where" and len(node.args) >= 3:
                return join(
                    self.dtype_of(node.args[1]), self.dtype_of(node.args[2])
                )
            if tail in _PAIR_ELEMENTWISE and len(node.args) >= 2:
                return join(
                    self.dtype_of(node.args[0]), self.dtype_of(node.args[1])
                )
            if tail in _JOIN_LIST and node.args:
                seq = node.args[0]
                if isinstance(seq, (ast.Tuple, ast.List)) and seq.elts:
                    out = self.dtype_of(seq.elts[0])
                    for e in seq.elts[1:]:
                        out = join(out, self.dtype_of(e))
                    return out
                return UNKNOWN
            if tail in _PASSTHROUGH:
                return self.dtype_of(node.args[0]) if node.args else UNKNOWN
            return UNKNOWN

        # --- numpy: strongly typed, float64 default ----------------------
        if _is_np(name):
            if explicit:
                return explicit
            if tail in _INT_RESULTS:
                return INT
            if tail in (_CONVERT | _CREATION | _LIKE | _REDUCTIONS | _PASSTHROUGH
                        | _PAIR_ELEMENTWISE | _MATMULS):
                arg_d = self.dtype_of(node.args[0]) if node.args else UNKNOWN
                if arg_d in (WEAK_FLOAT,):
                    return F64  # np hardens python floats to float64
                if arg_d == WEAK_INT:
                    return INT
                return arg_d
            return UNKNOWN

        # --- jax.random samplers ------------------------------------------
        if name.startswith("jax.random.") or _root(name) == "random":
            if tail in _RANDOM_SAMPLERS:
                return explicit or F32
            if tail in ("randint", "categorical", "choice", "permutation", "bernoulli"):
                return INT
            return UNKNOWN

        return UNKNOWN


def _chain_has_at(node: ast.AST) -> bool:
    while True:
        if isinstance(node, ast.Attribute):
            if node.attr == "at":
                return True
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            return False


# ------------------------------------------------------------ dtype policy

_POLICY_RE = re.compile(r"graftlint:\s*dtype-policy=([A-Za-z0-9_]+)")
_POLICY_ALIASES = {
    "bf16": BF16, "bfloat16": BF16,
    "f16": F16, "fp16": F16, "float16": F16,
    "f32": F32, "fp32": F32, "float32": F32,
    "f64": F64, "fp64": F64, "float64": F64,
}


@dataclasses.dataclass
class DtypePolicies:
    """Parsed ``# graftlint: dtype-policy=...`` declarations for one file:
    ``module`` (policy with no def following it) plus ``spans`` of
    ``(start, end, policy)`` for policies attached to a def."""

    module: Optional[str] = None
    spans: list = dataclasses.field(default_factory=list)

    def for_line(self, line: int) -> Optional[str]:
        """Innermost declared policy governing ``line`` (module fallback)."""
        best = None
        for start, end, policy in self.spans:
            if start <= line <= end and (best is None or start > best[0]):
                best = (start, policy)
        return best[1] if best else self.module


def parse_dtype_policies(source: str, tree: ast.AST) -> DtypePolicies:
    comments: list = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                m = _POLICY_RE.search(tok.string)
                if m:
                    policy = _POLICY_ALIASES.get(m.group(1).lower())
                    if policy:
                        comments.append((tok.start[0], policy))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return DtypePolicies()

    defs = sorted(
        (
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ),
        key=lambda n: n.lineno,
    )
    out = DtypePolicies()
    for line, policy in comments:
        target = next((d for d in defs if d.lineno > line), None)
        if target is None:
            out.module = policy
        else:
            out.spans.append(
                (target.lineno, target.end_lineno or target.lineno, policy)
            )
    return out


# ------------------------------------------------- reduced-context detection

_REDUCED_NAME_TAILS = {"bfloat16", "float16", "half"}


def reduced_hint(node: ast.AST) -> bool:
    """True when the body lexically mentions a reduced dtype (an
    ``astype(jnp.bfloat16)``, a ``dtype=jnp.bfloat16`` kwarg, a
    ``"bfloat16"`` string) — the opt-in signal for files with no declared
    policy."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _REDUCED_NAME_TAILS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _REDUCED_NAME_TAILS:
            return True
        if (
            isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)
            and sub.value in ("bfloat16", "float16")
        ):
            return True
    return False


def region_reduced(region, policies: DtypePolicies):
    """``(dtype, why)`` when the region is a reduced-precision context —
    via declared policy or lexical bf16 markers — else None. A declared
    full-precision policy (fp32/fp64) beats lexical markers: it is the
    opt-out for regions that merely mention reduced dtypes."""
    policy = policies.for_line(region.start)
    if policy is not None:
        if policy in REDUCED:
            return policy, f"dtype-policy={policy}"
        return None
    if reduced_hint(region.node):
        return BF16, "bf16 markers in body"
    return None
