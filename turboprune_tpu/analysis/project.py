"""Project-wide symbol table: modules, functions, and import resolution.

The per-file layer (regions.py) deliberately stops at module boundaries —
its documented blind spot is a function jitted at a distant call site
(train/steps.py step closures jitted inside parallel/mesh.py factories).
This module supplies the missing half: it parses every analyzed module
once, records every module-level function, every method, and every nested
def under a stable qualified name, and resolves the names a call site uses
(including relative imports and package ``__init__`` re-exports, the two
idioms this repo leans on) back to those definitions. callgraph.py builds
edges and jit-reachability on top; interproc.py turns both into findings.

Resolution is deliberately bounded — no type inference, no instance
attribute tracking. What IS resolved, because the repo's style makes it
both common and decidable:

* plain calls to module-level functions (same module or imported),
* dotted calls through module aliases (``masking.apply_masks``),
* ``self.method()`` inside a class body,
* re-export chains (``from .parallel import is_primary`` where
  parallel/__init__.py itself imports it from ``.multihost``),
* nested defs by name inside their enclosing function.

Everything else resolves to None and the interprocedural rules stay
silent — the contract is the same as the lexical layer's: zero false
negatives on the RESOLVED patterns, no claims about the rest.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Optional

from .regions import dotted_name, param_names

__all__ = ["FunctionInfo", "ModuleInfo", "ProjectIndex", "module_name_for"]

_MAX_RESOLVE_DEPTH = 16


def module_name_for(path) -> str:
    """Dotted module name, walking up through ``__init__.py`` packages.

    ``<root>/turboprune_tpu/train/steps.py`` -> "turboprune_tpu.train.steps";
    a file outside any package (tests/test_x.py) is just its stem."""
    p = Path(path).resolve()
    parts = [p.stem] if p.name != "__init__.py" else []
    d = p.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        parent = d.parent
        if parent == d:
            break
        d = parent
    return ".".join(parts) if parts else p.stem


@dataclasses.dataclass
class FunctionInfo:
    """One function/method/nested def the project knows by qualified name."""

    qualname: str  # module.func / module.Class.method / module.outer.inner
    modname: str
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    path: str
    class_name: Optional[str] = None
    parent: Optional[str] = None  # enclosing function qualname for nested defs
    is_bound_method: bool = False  # True: calls via self.m() skip param 0

    @property
    def params(self) -> list:
        return param_names(self.node)

    @property
    def positional_params(self) -> list:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    def arg_to_param(self, call: ast.Call, bound: bool) -> list:
        """Map a call's arguments onto this function's parameter names.

        Returns ``[(param_name, arg_expr), ...]``; unmatched *args/**kwargs
        style arguments are dropped (no claim is better than a wrong one).
        ``bound`` is True for ``obj.m(...)`` calls where the first positional
        parameter is the receiver."""
        pos = self.positional_params
        offset = 1 if (bound and self.is_bound_method and pos) else 0
        out = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            j = i + offset
            if j < len(pos):
                out.append((pos[j], arg))
        names = set(self.params)
        for kw in call.keywords:
            if kw.arg and kw.arg in names:
                out.append((kw.arg, kw.value))
        return out

    def location(self) -> str:
        return f"{self.path}:{self.node.lineno}"


def _has_decorator(node, name: str) -> bool:
    return any(
        dotted_name(d) is not None and dotted_name(d).rsplit(".", 1)[-1] == name
        for d in node.decorator_list
    )


@dataclasses.dataclass
class ModuleInfo:
    """One parsed module: its tree plus local name bindings from imports."""

    modname: str
    path: str
    tree: ast.Module
    is_package: bool  # file is an __init__.py
    bindings: dict = dataclasses.field(default_factory=dict)  # name -> symbol

    def _anchor(self, level: int) -> list:
        """Base package parts for a ``from .`` / ``from ..`` import."""
        parts = self.modname.split(".")
        if not self.is_package:
            parts = parts[:-1]
        cut = level - 1
        return parts[: len(parts) - cut] if cut else parts

    def record_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.bindings[alias.asname] = alias.name
                    else:
                        head = alias.name.split(".", 1)[0]
                        self.bindings[head] = head
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self._anchor(node.level)
                    target = ".".join(base + ([node.module] if node.module else []))
                else:
                    target = node.module or ""
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.bindings[local] = (
                        f"{target}.{alias.name}" if target else alias.name
                    )


class ProjectIndex:
    """Symbol table over a set of modules, with call-name resolution."""

    def __init__(self):
        self.modules: dict = {}  # modname -> ModuleInfo
        self.functions: dict = {}  # qualname -> FunctionInfo
        self.by_node: dict = {}  # id(ast node) -> FunctionInfo

    # ------------------------------------------------------------- building
    @classmethod
    def build(cls, contexts: Iterable) -> "ProjectIndex":
        """Index from parsed per-file contexts (anything with .path/.tree)."""
        index = cls()
        for ctx in contexts:
            index.add_module(ctx.path, ctx.tree)
        return index

    def add_module(self, path, tree: ast.Module) -> None:
        modname = module_name_for(path)
        mi = ModuleInfo(
            modname=modname,
            path=str(path),
            tree=tree,
            is_package=Path(path).name == "__init__.py",
        )
        mi.record_imports()
        self.modules[modname] = mi
        self._index_scope(mi, tree.body, prefix=modname, class_name=None)

    def _index_scope(self, mi, body, prefix: str, class_name, parent=None):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{node.name}"
                bound = class_name is not None and not _has_decorator(
                    node, "staticmethod"
                )
                fi = FunctionInfo(
                    qualname=qual,
                    modname=mi.modname,
                    name=node.name,
                    node=node,
                    path=mi.path,
                    class_name=class_name,
                    parent=parent,
                    is_bound_method=bound,
                )
                self.functions[qual] = fi
                self.by_node[id(node)] = fi
                # nested defs live under the function's qualname
                self._index_scope(
                    mi, node.body, prefix=qual, class_name=None, parent=qual
                )
            elif isinstance(node, ast.ClassDef):
                self._index_scope(
                    mi,
                    node.body,
                    prefix=f"{prefix}.{node.name}",
                    class_name=node.name,
                    parent=None,
                )
            elif isinstance(node, (ast.If, ast.Try, ast.With)):
                # conditional defs (TYPE_CHECKING guards, try/except imports)
                self._index_scope_stmts(node, mi, prefix, class_name, parent)

    def _index_scope_stmts(self, stmt, mi, prefix, class_name, parent):
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, field, None)
            if not sub:
                continue
            if field == "handlers":
                for h in sub:
                    self._index_scope(mi, h.body, prefix, class_name, parent)
            else:
                self._index_scope(mi, sub, prefix, class_name, parent)

    # ------------------------------------------------------------ resolving
    def function_for_node(self, node) -> Optional[FunctionInfo]:
        return self.by_node.get(id(node))

    def resolve_symbol(self, sym: str, _depth: int = 0) -> Optional[FunctionInfo]:
        """Follow a fully-dotted symbol through re-export chains."""
        if _depth > _MAX_RESOLVE_DEPTH:
            return None
        fi = self.functions.get(sym)
        if fi is not None:
            return fi
        # peel the longest module prefix, then follow its import bindings
        mod = sym
        while "." in mod:
            mod = mod.rsplit(".", 1)[0]
            mi = self.modules.get(mod)
            if mi is None:
                continue
            rest = sym[len(mod) + 1 :]
            head, _, tail = rest.partition(".")
            if head in mi.bindings:
                target = mi.bindings[head] + (f".{tail}" if tail else "")
                return self.resolve_symbol(target, _depth + 1)
            return self.functions.get(sym)
        return None

    def resolve_call(
        self,
        modinfo: ModuleInfo,
        func: ast.AST,
        scope: Optional[FunctionInfo] = None,
    ) -> Optional[FunctionInfo]:
        """Resolve a call's func expression to a project function, or None.

        ``scope`` is the enclosing function (for ``self.m()`` and nested-def
        resolution); None means module scope."""
        name = dotted_name(func)
        if not name:
            return None
        parts = name.split(".")
        # self.method() inside a class body
        if parts[0] == "self" and scope is not None and scope.class_name:
            if len(parts) == 2:
                return self.functions.get(
                    f"{scope.modname}.{scope.class_name}.{parts[1]}"
                )
            return None
        if len(parts) == 1:
            # nested def of an enclosing function (walk the parent chain)
            s = scope
            while s is not None:
                fi = self.functions.get(f"{s.qualname}.{parts[0]}")
                if fi is not None:
                    return fi
                s = self.functions.get(s.parent) if s.parent else None
            # sibling method referenced bare inside a class? (not a pattern
            # here — plain name next tries module level, then imports)
            fi = self.functions.get(f"{modinfo.modname}.{parts[0]}")
            if fi is not None:
                return fi
            if parts[0] in modinfo.bindings:
                return self.resolve_symbol(modinfo.bindings[parts[0]])
            return None
        # dotted: resolve the head through imports / local classes
        head, rest = parts[0], ".".join(parts[1:])
        if head in modinfo.bindings:
            return self.resolve_symbol(f"{modinfo.bindings[head]}.{rest}")
        # Class.method in the same module
        return self.functions.get(f"{modinfo.modname}.{name}")

    def module_for_path(self, path) -> Optional[ModuleInfo]:
        p = str(path)
        for mi in self.modules.values():
            if mi.path == p:
                return mi
        return None
