"""Interprocedural checks: the per-file rules, fired through call chains.

Five of the eight graftlint rules have failure modes that routinely live
one or more calls away from the pattern the per-file layer matches:

* ``jit-host-sync`` — the ``.item()``/``np.asarray`` sits in a helper
  (ops/masking.py) called from a jitted step, not in the step itself;
* ``collective-order`` — the call under ``if is_primary():`` is a benign-
  looking wrapper (``save_pytree``) whose callee graph ends in
  ``sync_global_devices``;
* ``rng-key-reuse`` — the key is consumed twice via a sampler HELPER, so
  no single scope ever hands it to jax.random twice;
* ``donated-arg-reuse`` — the donating jit is built by a factory in
  parallel/mesh.py, so the caller's scope never sees ``donate_argnums``;
* ``retrace-hazard`` — the jit is constructed inside a factory that a
  loop calls every iteration.

Each finding reuses the per-file rule id (same waiver syntax, same
``--select`` vocabulary) and carries a ``trace``: the call path from the
jit entry / rank branch / donation site to the flagged line, so a waiver
review can check the chain instead of trusting the tool. Findings that
duplicate a per-file finding at the same (file, line, rule) are dropped
by the driver — the lexical message is the more precise one.

Resolution limits are inherited from project.py: unresolved calls are
silent, resolved ones are exact. Depth bounds live in callgraph.py.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .callgraph import CallGraph, _fmt
from .core import RULES, is_test_file
from .project import ProjectIndex
from .regions import dotted_name
from .rules import (
    _COLLECTIVE_TAILS,
    _own_statements,
    _root,
    _tail,
    _walk_no_nested_defs,
    JitHostSyncRule,
    rank_conditional_test,
    RngKeyReuseRule,
    DonatedArgReuseRule,
    RetraceHazardRule,
)

__all__ = ["check_project", "ProjectView"]


class ProjectView:
    """What the per-file dataflow rules may ask the project about."""

    def __init__(self, graph: CallGraph, modinfo):
        self.graph = graph
        self.index = graph.index
        self.mi = modinfo

    def _scope_fi(self, scope_node):
        if scope_node is None:
            return None
        return self.index.function_for_node(scope_node)

    def rng_call_info(self, call: ast.Call, scope_node) -> Optional[list]:
        """For a call resolved to a project function: ``[(arg_expr,
        witness), ...]`` for the arguments bound to key-CONSUMING params
        (possibly empty — a resolved non-consumer). None = unresolved."""
        callee = self.index.resolve_call(self.mi, call.func, self._scope_fi(scope_node))
        if callee is None:
            return None
        consuming = self.graph.key_consuming_params(callee)
        bound = isinstance(call.func, ast.Attribute)
        return [
            (arg, f"{_fmt(callee)} -> {consuming[p]}")
            for p, arg in callee.arg_to_param(call, bound)
            if p in consuming
        ]

    def donating_spec(self, call: ast.Call, scope_node):
        """(argnums, argnames, witness) when the call's callee is a
        donating-jit factory; else None."""
        callee = self.index.resolve_call(self.mi, call.func, self._scope_fi(scope_node))
        if callee is None:
            return None
        return self.graph.donating_factory(callee)


def _region_spans(graph: CallGraph, modname: str) -> list:
    return [
        (r.start, r.end) for r in graph.regions_by_module.get(modname, ())
    ]


def _in_spans(line: int, spans) -> bool:
    return any(s <= line <= e for s, e in spans)


def _host_sync_findings(graph: CallGraph, contexts) -> Iterator:
    """Unconditional host syncs in functions that are jit-reachable but
    not lexically marked (the lexical layer already covers those)."""
    rule = JitHostSyncRule()
    lexical_nodes = {
        id(r.node)
        for regions in graph.regions_by_module.values()
        for r in regions
    }
    for qual, reach in graph.reachable.items():
        fi = graph.index.functions.get(qual)
        if fi is None or id(fi.node) in lexical_nodes:
            continue
        ctx = contexts.get(fi.path)
        if ctx is None:
            continue
        spans = _region_spans(graph, fi.modname)
        trace = reach.trace()
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if _in_spans(node.lineno, spans):
                continue  # lexically-traced sub-region; per-file covers it
            f = node.func
            name = dotted_name(f)
            msg = None
            if isinstance(f, ast.Attribute) and f.attr in rule._SYNC_METHODS:
                msg = (
                    f".{f.attr}() in {fi.name}(), which is jit-reachable — "
                    "device->host sync inside compiled code; hoist it past "
                    "the jit boundary"
                )
            elif _tail(name) == "device_get" and _root(name) in (
                "jax",
                "device_get",
            ):
                msg = (
                    f"jax.device_get in jit-reachable {fi.name}() — host "
                    "transfer in a compiled body; hoist it to the caller"
                )
            elif (
                _root(name) in rule._NUMPY_ROOTS
                and _tail(name) in rule._NUMPY_PULLS
            ):
                msg = (
                    f"{name}(...) in jit-reachable {fi.name}() — numpy "
                    "materializes on host; use jnp"
                )
            if msg:
                yield ctx.finding(
                    rule,
                    node,
                    msg,
                    trace=trace + [f"{fi.name} ({fi.path}:{node.lineno})"],
                )


def _collective_findings(graph: CallGraph, contexts) -> Iterator:
    """Calls under a rank-conditional branch whose callees (transitively)
    issue a collective. Direct collective names under the branch are the
    per-file rule's job and are skipped here."""
    rule_obj = RULES["collective-order"]
    index = graph.index
    for mi in index.modules.values():
        ctx = contexts.get(mi.path)
        if ctx is None:
            continue
        scopes = [(None, mi.tree.body)]
        scopes.extend(
            (fi, fi.node.body)
            for fi in index.functions.values()
            if fi.path == mi.path
        )
        seen: set = set()
        for scope, body in scopes:
            for node in _walk_no_nested_defs(_own_statements(body)):
                if not isinstance(node, ast.If) or not rank_conditional_test(node):
                    continue
                for branch in (node.body, node.orelse):
                    for stmt in branch:
                        for sub in ast.walk(stmt):
                            if not isinstance(sub, ast.Call):
                                continue
                            if _tail(dotted_name(sub.func)) in _COLLECTIVE_TAILS:
                                continue  # per-file rule's finding
                            callee = index.resolve_call(mi, sub.func, scope)
                            if callee is None:
                                continue
                            witness = graph.collective_witness(callee)
                            if witness is None:
                                continue
                            key = (sub.lineno, sub.col_offset)
                            if key in seen:
                                continue
                            seen.add(key)
                            chain = [
                                f"{_fmt(callee)} called at "
                                f"{mi.path}:{sub.lineno}"
                            ] + witness
                            yield ctx.finding(
                                rule_obj,
                                sub,
                                f"{dotted_name(sub.func)}(...) under a "
                                "process_index()/is_primary() branch "
                                "transitively issues a collective "
                                f"({' -> '.join(witness)}) — hosts that "
                                "skip the branch never post it and the pod "
                                "deadlocks; run it on every host",
                                trace=chain,
                            )


def _retrace_findings(graph: CallGraph, contexts) -> Iterator:
    """Loop call sites of functions that build a fresh jit on every call
    (cross-module factory-in-loop). Cache-guarded constructions are
    already filtered out by the summary."""
    rule = RetraceHazardRule()
    index = graph.index
    for mi in index.modules.values():
        ctx = contexts.get(mi.path)
        if ctx is None or ctx.is_test:
            continue  # rule.skip_in_tests
        scopes = [(None, mi.tree.body)]
        scopes.extend(
            (fi, fi.node.body)
            for fi in index.functions.values()
            if fi.path == mi.path
        )
        for scope, body in scopes:
            yield from _retrace_scan(
                rule, ctx, mi, index, graph, scope, _own_statements(body), 0
            )


def _retrace_scan(rule, ctx, mi, index, graph, scope, stmts, loops) -> Iterator:
    for stmt in stmts:
        in_loop = loops + (
            1 if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)) else 0
        )
        if in_loop:
            for node in _walk_no_nested_defs([stmt]):
                if not isinstance(node, ast.Call):
                    continue
                callee = index.resolve_call(mi, node.func, scope)
                if callee is None:
                    continue
                hit = graph.constructs_jit(callee)
                if hit is None:
                    continue
                _line, witness = hit
                yield ctx.finding(
                    rule,
                    node,
                    f"{dotted_name(node.func)}(...) called in a loop "
                    f"builds a fresh jit every iteration ({witness}) — "
                    "hoist the factory call out of the loop or cache its "
                    "result",
                    trace=[witness],
                )
        else:
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    yield from _retrace_scan(
                        rule, ctx, mi, index, graph, scope,
                        _own_statements(sub), loops,
                    )
            for h in getattr(stmt, "handlers", []) or []:
                yield from _retrace_scan(
                    rule, ctx, mi, index, graph, scope,
                    _own_statements(h.body), loops,
                )


def check_project(index: ProjectIndex, contexts: dict) -> Iterator:
    """All interprocedural findings over the indexed project.

    ``contexts`` maps file path -> ModuleContext (the same parsed trees
    the per-file pass used)."""
    graph = CallGraph(index)

    findings: list = list(_host_sync_findings(graph, contexts))
    findings.extend(_collective_findings(graph, contexts))
    findings.extend(_retrace_findings(graph, contexts))

    # dtype-flow through call chains (helpers reached from reduced entries)
    from .dtype_rules import dtype_project_findings

    findings.extend(dtype_project_findings(graph, contexts))

    # shape-flow through call chains (helpers reached from jit entries)
    from .shape_rules import shape_project_findings

    findings.extend(shape_project_findings(graph, contexts))

    # concurrency layer: thread model + locksets (project-only rules)
    from .concurrency_rules import concurrency_findings

    findings.extend(concurrency_findings(index, contexts))

    # dataflow rules re-run with the project view (duplicates of the
    # per-file pass are dropped by the caller)
    rng = RngKeyReuseRule()
    donated = DonatedArgReuseRule()
    for mi in index.modules.values():
        ctx = contexts.get(mi.path)
        if ctx is None:
            continue
        view = ProjectView(graph, mi)
        findings.extend(rng.check_project(ctx, view))
        findings.extend(donated.check_project(ctx, view))

    for f in findings:
        rule = RULES.get(f.rule)
        if rule is not None and rule.skip_in_tests and is_test_file(f.file):
            continue
        yield f
