"""Pytest integration for graftsan (sanitizer.py).

``graftsan`` is a fixture, not an autouse hook: a test opts in, drives
whatever concurrent machinery it wants through the package's real code
paths, and gets the observed lock-order graph and write log to assert on.
At teardown the fixture fails the test on any observed lock-order cycle —
the property no test should ever waive — while race verdicts are left to
the test body (the CLI's ``--sanitize`` owns the static-diff contract).

tests/conftest.py re-exports the fixture so every test file sees it
without a ``pytest_plugins`` declaration.
"""

from __future__ import annotations

import pytest

from .sanitizer import Graftsan


@pytest.fixture
def graftsan():
    """Yields an ACTIVE Graftsan (factories patched); asserts zero observed
    lock-order cycles at teardown."""
    san = Graftsan()
    with san:
        yield san
    cycles = san.cycles()
    assert not cycles, f"graftsan observed lock-order cycle(s): {cycles}"
