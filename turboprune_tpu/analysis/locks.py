"""Lockset abstract interpretation over ``with lock:`` / acquire-release
regions, plus the interprocedural lock-acquisition-order graph.

This is the concurrency half of what regions.py does for jit tracing: a
lexically-decidable approximation of a dynamic property. A lock is "known"
when its identity is decidable the same way project.py decides call
targets — a ``self._lock`` attribute assigned ``threading.Lock()`` (or
RLock/Condition) in a method of the class, a module-level global, or a
function local (including enclosing-function locals, for closure workers).
``with self._lock:`` pushes it for the body; a statement-level
``lock.acquire()`` / ``lock.release()`` pair tracks linearly within one
statement list. Everything else (locks passed as parameters, locks fetched
from containers, ``with self._factory(key):``) is NOT tracked, and the
rules built on top stay silent there — same zero-false-positive contract
as the rest of graftlint.

Two other products live here because they need the same declared-type
scan:

* ``# guarded-by: <lock>`` comments on ``self.X = ...`` assignments — the
  machine-checked documentation of which lock protects which shared field
  (consumed by the unsynchronized-shared-mutation rule, rendered in
  README's catalog);
* per-function summaries (attribute accesses with the lockset held at the
  access, call sites with the lockset held at the call, acquisitions with
  the locks already held) that concurrency_rules.py and threads.py turn
  into findings.

Approximations, by design (documented here once, relied on by the rule
fixtures):

* container METHOD calls (``self._ring.append(x)``) count as reads of the
  binding, not writes — CPython makes single deque/dict ops atomic, and
  flagging every queue/deque use would bury the real signal. Rebinds
  (``self.x = v``) and subscript stores (``self.d[k] = v``, including
  ``+=``) are writes.
* an acquire inside a branch does not extend the lockset past the branch
  (under-approximation of "held": no false "is guarded" claims leak out of
  an If arm, at the cost of missing branch-balanced hand-rolled locking).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Iterator, Optional

from .project import FunctionInfo, ProjectIndex
from .regions import dotted_name
from .rules import _own_statements, _root, _tail

__all__ = [
    "AttrAccess",
    "Acquisition",
    "CallSite",
    "CheckThenAct",
    "DeclaredTypes",
    "FuncLockInfo",
    "LOCK_KINDS",
    "LockAnalysis",
    "OrderEdge",
    "build_order_graph",
    "collect_declared_types",
    "collect_guards",
    "ctor_kind",
    "find_cycles",
    "parse_guard_comments",
]

_MAX_DEPTH = 10

# Constructor tail -> declared kind. Bare tails are accepted (the repo
# imports ThreadPoolExecutor unqualified); dotted tails must hang off a
# stdlib concurrency root so ``mylib.Queue()`` stays untyped.
_CTOR_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Event": "event",
    "Barrier": "event",
    "Queue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "Thread": "thread",
    "Timer": "thread",
    "ThreadPoolExecutor": "pool",
    "ProcessPoolExecutor": "pool",
}
_CTOR_ROOTS = {"threading", "queue", "multiprocessing", "concurrent", "futures"}

# Kinds that participate in locksets (an Event/Queue is synchronization,
# but holding one is not a critical section).
LOCK_KINDS = frozenset({"lock", "rlock", "condition"})


def ctor_kind(node: ast.AST) -> Optional[str]:
    """``threading.Lock()`` -> "lock"; None for non-sync constructors."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    tail = _tail(name)
    if tail not in _CTOR_KINDS:
        return None
    if "." in name and _root(name) not in _CTOR_ROOTS:
        return None
    return _CTOR_KINDS[tail]


def _assign_targets(stmt: ast.AST) -> list:
    """(target_expr, value) pairs for Assign/AnnAssign statements."""
    if isinstance(stmt, ast.Assign):
        return [(t, stmt.value) for t in stmt.targets]
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [(stmt.target, stmt.value)]
    return []


@dataclasses.dataclass
class DeclaredTypes:
    """Where sync objects live: class attributes and module globals."""

    class_attrs: dict  # ("mod.Class", attr) -> kind
    module_names: dict  # (modname, name) -> kind

    def attr_kind(self, class_qual: str, attr: str) -> Optional[str]:
        return self.class_attrs.get((class_qual, attr))


def collect_declared_types(index: ProjectIndex) -> DeclaredTypes:
    class_attrs: dict = {}
    module_names: dict = {}
    for mi in index.modules.values():
        for stmt in mi.tree.body:
            for target, value in _assign_targets(stmt):
                kind = ctor_kind(value)
                if kind and isinstance(target, ast.Name):
                    module_names[(mi.modname, target.id)] = kind
    for fi in index.functions.values():
        if fi.class_name is None:
            continue
        cq = f"{fi.modname}.{fi.class_name}"
        for node in ast.walk(fi.node):
            for target, value in _assign_targets(node):
                kind = ctor_kind(value)
                if (
                    kind
                    and isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    class_attrs.setdefault((cq, target.attr), kind)
    return DeclaredTypes(class_attrs=class_attrs, module_names=module_names)


# --------------------------------------------------------------- guarded-by

_GUARD_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


def parse_guard_comments(source: str) -> dict:
    """line -> (lock attribute name, standalone) from ``# guarded-by: _lock``
    comments (tokenizer-based, same as waiver parsing: a ``#`` in a string
    is not a comment). ``standalone`` is True for comment-only lines — only
    those may annotate the assignment BELOW them; an inline guard on the
    previous attribute's assignment must not leak downward."""
    out: dict = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                m = _GUARD_RE.search(tok.string)
                if m:
                    standalone = tok.line.lstrip().startswith("#")
                    out[tok.start[0]] = (m.group(1), standalone)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}
    return out


def collect_guards(index: ProjectIndex, contexts: dict) -> dict:
    """("mod.Class", attr) -> guarding lock attribute name.

    A guard comment annotates the ``self.X = ...`` assignment that
    initializes the field: inline on any line of the assignment, or on a
    standalone comment line directly above it."""
    guards: dict = {}
    by_path: dict = {}
    for fi in index.functions.values():
        if fi.class_name is not None:
            by_path.setdefault(fi.path, []).append(fi)
    for path, fis in by_path.items():
        ctx = contexts.get(path)
        if ctx is None:
            continue
        comments = parse_guard_comments(ctx.source)
        if not comments:
            continue
        for fi in fis:
            cq = f"{fi.modname}.{fi.class_name}"
            for node in ast.walk(fi.node):
                for target, _value in _assign_targets(node):
                    if not (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        continue
                    span = range(
                        node.lineno - 1, (node.end_lineno or node.lineno) + 1
                    )
                    for line in span:
                        hit = comments.get(line)
                        if hit is None:
                            continue
                        lock, standalone = hit
                        if line < node.lineno and not standalone:
                            continue  # inline guard of the line above
                        guards[(cq, target.attr)] = lock
                        break
    return guards


# ------------------------------------------------------- per-function walk


@dataclasses.dataclass
class AttrAccess:
    """One ``self.X`` touch, with the lockset held at that point."""

    attr: str
    write: bool
    line: int
    held: frozenset  # lock ids


@dataclasses.dataclass
class Acquisition:
    lock: str  # lock id
    kind: str  # "lock" | "rlock" | "condition"
    line: int
    held_before: tuple  # lock ids already held when this one is taken


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    line: int
    held: frozenset  # lock ids (may be empty)


@dataclasses.dataclass
class CheckThenAct:
    """``if k not in self.d: self.d[k] = ...`` with the lockset at the If."""

    attr: str
    line: int
    held: frozenset


@dataclasses.dataclass
class FuncLockInfo:
    accesses: list  # [AttrAccess]
    acquisitions: list  # [Acquisition]
    calls: list  # [CallSite]
    check_then_acts: list  # [CheckThenAct]
    local_types: dict  # local name -> kind (sync ctors assigned in-body)


_MUTATING_CTX = (ast.Store, ast.Del)


class LockAnalysis:
    """Memoized lockset walks + transitive-acquisition summaries."""

    def __init__(self, index: ProjectIndex, contexts: dict):
        self.index = index
        self.types = collect_declared_types(index)
        self.guards = collect_guards(index, contexts)
        self._info: dict = {}
        self._acq_memo: dict = {}

    # ------------------------------------------------------------ identity
    def lock_name(self, class_qual: str, attr: str) -> str:
        return f"{class_qual}.{attr}"

    def declared_kind(
        self, expr: ast.AST, fi: Optional[FunctionInfo]
    ) -> Optional[tuple]:
        """(lock_id, kind) for a decidable sync-object expression; None
        otherwise. Covers ``self.X``, module globals, function locals and
        enclosing-function locals (closures)."""
        name = dotted_name(expr)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and fi and fi.class_name:
            cq = f"{fi.modname}.{fi.class_name}"
            kind = self.types.attr_kind(cq, parts[1])
            if kind:
                return (f"{cq}.{parts[1]}", kind)
            return None
        if len(parts) == 1:
            s = fi
            while s is not None:
                kind = self.info(s).local_types.get(parts[0])
                if kind:
                    return (f"{s.qualname}.<local>.{parts[0]}", kind)
                s = (
                    self.index.functions.get(s.parent)
                    if s.parent
                    else None
                )
            if fi is not None:
                kind = self.types.module_names.get((fi.modname, parts[0]))
                if kind:
                    return (f"{fi.modname}.{parts[0]}", kind)
        return None

    def lock_id(
        self, expr: ast.AST, fi: Optional[FunctionInfo]
    ) -> Optional[tuple]:
        """declared_kind restricted to lockset-participating kinds."""
        hit = self.declared_kind(expr, fi)
        if hit and hit[1] in LOCK_KINDS:
            return hit
        return None

    # ------------------------------------------------------------- walking
    def info(self, fi: FunctionInfo) -> FuncLockInfo:
        cached = self._info.get(fi.qualname)
        if cached is not None:
            return cached
        info = FuncLockInfo([], [], [], [], {})
        self._info[fi.qualname] = info  # pre-seed: local lookup may re-enter
        for stmt in _own_statements(fi.node.body):
            for target, value in _assign_targets(stmt):
                kind = ctor_kind(value)
                if kind and isinstance(target, ast.Name):
                    info.local_types.setdefault(target.id, kind)
        self._walk_stmts(fi, info, _own_statements(fi.node.body), [])
        return info

    def _walk_stmts(self, fi, info, stmts, held) -> None:
        """``held`` is a list of (lock_id, kind); linear acquire/release at
        statement level mutates the local copy so later statements in the
        SAME list see it."""
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = []
                for item in stmt.items:
                    self._scan_expr(fi, info, item.context_expr, held)
                    lid = self.lock_id(item.context_expr, fi)
                    if lid:
                        info.acquisitions.append(
                            Acquisition(
                                lid[0],
                                lid[1],
                                stmt.lineno,
                                tuple(l for l, _ in held),
                            )
                        )
                        pushed.append(lid)
                self._walk_stmts(
                    fi, info, _own_statements(stmt.body), held + pushed
                )
                continue
            hit = self._acquire_release(stmt, fi)
            if hit is not None:
                lid, op = hit
                if op == "acquire":
                    info.acquisitions.append(
                        Acquisition(
                            lid[0],
                            lid[1],
                            stmt.lineno,
                            tuple(l for l, _ in held),
                        )
                    )
                    held.append(lid)
                else:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == lid[0]:
                            del held[i]
                            break
                continue
            if isinstance(stmt, ast.If):
                cta = self._check_then_act(stmt)
                if cta is not None:
                    info.check_then_acts.append(
                        CheckThenAct(
                            cta,
                            stmt.lineno,
                            frozenset(l for l, _ in held),
                        )
                    )
            # compound statements: scan this level's expressions, recurse
            # into bodies with the current lockset
            compound = False
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and isinstance(sub, list):
                    compound = True
                    self._walk_stmts(fi, info, _own_statements(sub), held)
            for h in getattr(stmt, "handlers", []) or []:
                compound = True
                self._walk_stmts(fi, info, _own_statements(h.body), held)
            if compound:
                for field in ("test", "iter", "subject"):
                    sub = getattr(stmt, field, None)
                    if sub is not None:
                        self._scan_expr(fi, info, sub, held)
            else:
                self._scan_expr(fi, info, stmt, held)

    def _acquire_release(self, stmt, fi) -> Optional[tuple]:
        """((lock_id, kind), "acquire"|"release") for a statement-level
        ``lock.acquire()`` / ``ok = lock.acquire(...)`` / ``lock.release()``."""
        value = None
        if isinstance(stmt, ast.Expr):
            value = stmt.value
        elif isinstance(stmt, ast.Assign):
            value = stmt.value
        if not (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr in ("acquire", "release")
        ):
            return None
        lid = self.lock_id(value.func.value, fi)
        if lid is None:
            return None
        return lid, value.func.attr

    def _scan_expr(self, fi, info, node, held) -> None:
        """Record attribute accesses and call sites in one statement or
        expression, without descending into nested defs (own scopes)."""
        held_ids = frozenset(l for l, _ in held)
        subscript_writes: set = set()
        stack = [node]
        flat: list = []
        while stack:
            n = stack.pop()
            flat.append(n)
            for child in ast.iter_child_nodes(n):
                if isinstance(
                    child,
                    (
                        ast.FunctionDef,
                        ast.AsyncFunctionDef,
                        ast.ClassDef,
                        ast.Lambda,
                    ),
                ):
                    continue
                stack.append(child)
        for n in flat:
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.ctx, _MUTATING_CTX)
                and isinstance(n.value, ast.Attribute)
            ):
                subscript_writes.add(id(n.value))
        cq = (
            f"{fi.modname}.{fi.class_name}"
            if fi is not None and fi.class_name
            else None
        )
        for n in flat:
            if isinstance(n, ast.Call):
                info.calls.append(CallSite(n, n.lineno, held_ids))
            elif (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
            ):
                write = isinstance(n.ctx, _MUTATING_CTX) or id(n) in (
                    subscript_writes
                )
                if not write and cq is not None:
                    # using (not rebinding) a declared sync object is not a
                    # shared-state access
                    if self.types.attr_kind(cq, n.attr):
                        continue
                info.accesses.append(
                    AttrAccess(n.attr, write, n.lineno, held_ids)
                )

    @staticmethod
    def _check_then_act(stmt: ast.If) -> Optional[str]:
        """The ``self.<attr>`` container of an
        ``if k not in self.d: self.d[k] = ...`` (or ``.get(k) is None``)
        pattern; None when the If is not that shape."""
        test = stmt.test
        container = None
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.NotIn)
        ):
            container = dotted_name(test.comparators[0])
        elif (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Is)
            and isinstance(test.left, ast.Call)
            and isinstance(test.left.func, ast.Attribute)
            and test.left.func.attr == "get"
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            container = dotted_name(test.left.func.value)
        if not container:
            return None
        parts = container.split(".")
        if len(parts) != 2 or parts[0] != "self":
            return None
        attr = parts[1]
        for sub in ast.walk(stmt):
            if (
                isinstance(sub, ast.Subscript)
                and isinstance(sub.ctx, ast.Store)
                and dotted_name(sub.value) == container
            ):
                return attr
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("add", "append", "setdefault", "update")
                and dotted_name(sub.func.value) == container
            ):
                return attr
        return None

    # ----------------------------------------------------------- summaries
    def transitive_acquires(
        self, fi: FunctionInfo, _depth: int = 0
    ) -> dict:
        """lock_id -> (kind, witness hops) for every tracked lock this
        function may take, directly or through resolved callees. Memoized;
        cycles short-circuit to the partial result."""
        memo = self._acq_memo.get(fi.qualname)
        if memo is not None:
            return memo
        out: dict = {}
        self._acq_memo[fi.qualname] = out  # cycle guard
        info = self.info(fi)
        for a in info.acquisitions:
            out.setdefault(
                a.lock,
                (
                    a.kind,
                    [f"{fi.name} acquires {a.lock} ({fi.path}:{a.line})"],
                ),
            )
        if _depth >= _MAX_DEPTH:
            return out
        mi = self.index.modules.get(fi.modname)
        if mi is None:
            return out
        for cs in info.calls:
            callee = self.index.resolve_call(mi, cs.node.func, fi)
            if callee is None or callee.qualname == fi.qualname:
                continue
            for lid, (kind, wit) in self.transitive_acquires(
                callee, _depth + 1
            ).items():
                out.setdefault(
                    lid,
                    (
                        kind,
                        [
                            f"{fi.name} -> {callee.name} "
                            f"({fi.path}:{cs.line})"
                        ]
                        + wit,
                    ),
                )
        return out


# ------------------------------------------------------------- order graph


@dataclasses.dataclass
class OrderEdge:
    """src held while dst is acquired, with the first witness site."""

    src: str
    dst: str
    file: str
    line: int
    witness: list  # human-readable hops


def build_order_graph(analysis: LockAnalysis) -> dict:
    """(src, dst) -> OrderEdge over the whole project. A self-edge
    (L, L) means a non-reentrant lock is re-acquired while held — an
    immediate deadlock, reported by the same cycle rule. Reentrant locks
    (RLock, and Conditions built on them) do not self-edge."""
    edges: dict = {}

    def add(src, dst, file, line, witness):
        edges.setdefault(
            (src, dst), OrderEdge(src, dst, file, line, witness)
        )

    for qual in sorted(analysis.index.functions):
        fi = analysis.index.functions[qual]
        info = analysis.info(fi)
        for a in info.acquisitions:
            for h in a.held_before:
                if h != a.lock:
                    add(
                        h,
                        a.lock,
                        fi.path,
                        a.line,
                        [
                            f"{fi.name} holds {h} and acquires "
                            f"{a.lock} ({fi.path}:{a.line})"
                        ],
                    )
                elif a.kind == "lock":
                    add(
                        h,
                        a.lock,
                        fi.path,
                        a.line,
                        [
                            f"{fi.name} re-acquires non-reentrant "
                            f"{a.lock} while holding it "
                            f"({fi.path}:{a.line})"
                        ],
                    )
        mi = analysis.index.modules.get(fi.modname)
        if mi is None:
            continue
        for cs in info.calls:
            if not cs.held:
                continue
            callee = analysis.index.resolve_call(mi, cs.node.func, fi)
            if callee is None or callee.qualname == fi.qualname:
                continue
            for lid, (kind, wit) in analysis.transitive_acquires(
                callee
            ).items():
                for h in sorted(cs.held):
                    hop = (
                        f"{fi.name} holds {h} and calls {callee.name} "
                        f"({fi.path}:{cs.line})"
                    )
                    if h != lid:
                        add(h, lid, fi.path, cs.line, [hop] + wit)
                    elif kind == "lock":
                        add(h, lid, fi.path, cs.line, [hop] + wit)
    return edges


def find_cycles(edges: dict) -> list:
    """Deterministic list of cycles in the acquisition-order graph, each a
    list of lock ids (``[a, b]`` = a->b->a; ``[a]`` = self-deadlock).
    One representative cycle per strongly-connected component."""
    adj: dict = {}
    for src, dst in edges:
        adj.setdefault(src, []).append(dst)
        adj.setdefault(dst, [])
    for dsts in adj.values():
        dsts.sort()

    # Tarjan's SCC, iterative, deterministic over sorted nodes.
    index_of: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(root):
        work = [(root, iter(adj[root]))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index_of:
                    index_of[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(adj[nxt])))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index_of[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index_of[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(sorted(comp))

    for node in sorted(adj):
        if node not in index_of:
            strongconnect(node)

    cycles: list = []
    for comp in sccs:
        if len(comp) > 1:
            cycles.append(comp)
        elif (comp[0], comp[0]) in edges:
            cycles.append(comp)
    cycles.sort()
    return cycles


def cycle_witness(cycle: list, edges: dict) -> Iterator:
    """The OrderEdges backing one cycle, in a stable order."""
    nodes = set(cycle)
    for key in sorted(edges):
        if key[0] in nodes and key[1] in nodes:
            yield edges[key]
