"""Shared runtime-target resolution for graftlint's dynamic modes.

Three modes mirror the static rules against a real run — ``--jaxpr-audit``
(dtype rules vs the traced jaxpr), ``--sanitize`` (thread rules vs observed
locks), and ``--compile-audit`` (shape rules / executable manifest vs the
compiles XLA actually performs). Each accepts a target spec; before this
module each reimplemented spec parsing with drifting semantics (the
sanitizer accepted only ``file.py:builder`` while the jaxpr audit also took
``pkg.module:builder``). Now all three resolve through one registry:

* a mode-specific table of NAMED targets (``train``/``eval`` step entries,
  the ``pipeline``/``fleet``/``serve`` load drivers);
* ``path/to/file.py:builder`` — load the file, call ``builder()``;
* ``pkg.module:builder`` — import the module, call ``builder()``.

The shared synthetic train/eval step entry (tiny resnet18, CIFAR-shaped
inputs, fixed PRNG key) also lives here: the jaxpr audit traces it and the
compile audit jits it, so both gates measure the same program.

jax imports stay inside functions — the analysis package must import with
no accelerator stack; only the runtime modes pay for the tracer.
"""

from __future__ import annotations

import importlib
import importlib.util
from pathlib import Path
from typing import Optional

__all__ = [
    "TargetError",
    "default_step_entry",
    "load_builder",
    "resolve_runtime_target",
]


class TargetError(RuntimeError):
    """Bad target spec (CLI modes map their subclass to exit code 2)."""


def load_builder(
    spec: str, error_cls=TargetError, what: str = "target"
) -> tuple:
    """``(builder, static_paths)`` for a ``file.py:fn`` / ``pkg.module:fn``
    spec. ``static_paths`` is the file list a mode's static half should
    analyze alongside the runtime run (the defining file)."""
    mod_part, sep, fn_name = spec.rpartition(":")
    if not sep or not mod_part or not fn_name:
        raise error_cls(
            f"bad {what} {spec!r}: expected 'path/to/file.py:builder' or "
            "'pkg.module:builder'"
        )
    if mod_part.endswith(".py"):
        path = Path(mod_part)
        if not path.is_file():
            raise error_cls(f"{what}: no such file: {path}")
        mod_spec = importlib.util.spec_from_file_location(path.stem, path)
        mod = importlib.util.module_from_spec(mod_spec)
        mod_spec.loader.exec_module(mod)
        static_paths = [path]
    else:
        try:
            mod = importlib.import_module(mod_part)
        except ImportError as e:
            raise error_cls(f"{what}: cannot import {mod_part!r}: {e}") from e
        static_paths = [Path(mod.__file__)]
    builder = getattr(mod, fn_name, None)
    if builder is None:
        raise error_cls(f"{what}: {mod_part} has no {fn_name!r}")
    return builder, static_paths


def resolve_runtime_target(
    spec: str,
    named: dict,
    error_cls=TargetError,
    what: str = "target",
    load: bool = True,
) -> tuple:
    """``("named", named[spec])`` or ``("builder", (builder, paths))``.

    ``named`` maps target names to mode-specific payloads (a driver
    callable, an entry kind — whatever the mode keys on). Anything else
    with a ``:`` resolves as a builder spec; anything else is a usage
    error that lists the names, so every mode rejects typos the same way.

    ``load=False`` defers the import: ``("builder", spec)`` comes back
    after the grammar check only, for modes that must not execute the
    target module until their instrumented window is open.
    """
    if spec in named:
        return "named", named[spec]
    if ":" in spec:
        if not load:
            return "builder", spec
        return "builder", load_builder(spec, error_cls=error_cls, what=what)
    raise error_cls(
        f"unknown {what} {spec!r}; expected one of "
        f"{', '.join(sorted(named))}, 'path/to/file.py:builder' or "
        "'pkg.module:builder'"
    )


def default_step_entry(kind: str, policy: str = "fp32") -> tuple:
    """``(step_fn, args)`` for the synthetic-task train/eval step: tiny
    resnet18, CIFAR-shaped inputs. The jaxpr audit traces it, the compile
    audit jits and runs it — one program, two mirrors."""
    import jax
    import jax.numpy as jnp

    from ..train import create_train_state, make_eval_step, make_train_step, sgd
    from ..models import create_model

    model = create_model("resnet18", num_classes=10, dataset_name="CIFAR10")
    tx = sgd(0.1, momentum=0.9, weight_decay=5e-4)
    state = create_train_state(
        # graftlint: disable=rng-key-reuse -- fixed key: the audits are reproducible gates, not samplers
        model, tx, jax.random.key(0), input_shape=(2, 8, 8, 3)
    )
    images = jnp.zeros((2, 8, 8, 3), jnp.float32)
    if policy in ("bf16", "bfloat16"):
        images = images.astype(jnp.bfloat16)
    labels = jnp.zeros((2,), jnp.int32)
    fn = make_train_step(model, tx) if kind == "train" else make_eval_step(model)
    return fn, (state, (images, labels))
