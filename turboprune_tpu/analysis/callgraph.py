"""Call graph + jit-reachability + interprocedural function summaries.

Built on project.py's symbol table. Three products, all consumed by
interproc.py:

1. **Edges** — ``caller -> (callee, line)`` for every resolved call, plus
   "passed as a callback" edges (a project function handed to another call
   is assumed invokable there; over-approximate on purpose, reachability
   wants no false negatives on resolved names).

2. **Jit entries** — functions whose bodies end up traced. Lexical entries
   come straight from regions.py; the interprocedural ones are the repo's
   two factory idioms that the lexical layer documents as its blind spot:

   * higher-order jitting — ``make_sharded_train_step(step, mesh)`` where
     the factory's body does ``jax.jit(step, ...)``: the argument bound to
     the jitted parameter becomes an entry;
   * closure factories — ``raw = make_train_step(...)`` returns a nested
     def, so when ``raw`` later flows into a jit (directly or via a
     higher-order factory) the NESTED function is the entry, and its
     callees (ops/masking, pruning/criteria, ...) become jit-reachable.

3. **Summaries** — per-function facts the upgraded rules consume:
   which params a function jits, whether it returns a nested def or a
   donating jit, which key params it (transitively) consumes, whether it
   (transitively) issues a collective, and whether it constructs a fresh
   jit wrapper unconditionally on every call. Each summary memoizes and
   carries a witness path so findings can print WHERE the sink is.

Depth is bounded (``MAX_DEPTH``) and cycles short-circuit: the analysis
must terminate on any input, including mutually recursive helpers.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .project import FunctionInfo, ModuleInfo, ProjectIndex
from .regions import (
    build_jit_regions,
    donation_spec,
    dotted_name,
    is_jit_wrapper,
    is_tracing_call,
    unwrap_partial,
)
from .rules import (
    _COLLECTIVE_TAILS,
    _KEY_DERIVERS,
    _is_jax_random,
    _names_directly_under,
    _own_statements,
    _tail,
    _walk_no_nested_defs,
)

__all__ = ["CallGraph", "MAX_DEPTH"]

MAX_DEPTH = 10


def _fmt(fi: FunctionInfo) -> str:
    return f"{fi.name} ({fi.location()})"


@dataclasses.dataclass
class Reach:
    """How a function becomes jit-traced: the entry plus the call chain."""

    entry: FunctionInfo
    entry_reason: str
    path: tuple  # ((FunctionInfo, call line), ...) from entry to target

    def trace(self) -> list:
        hops = [f"jit entry {_fmt(self.entry)} [{self.entry_reason}]"]
        hops.extend(f"{_fmt(fi)} called at line {line}" for fi, line in self.path)
        return hops


class CallGraph:
    def __init__(self, index: ProjectIndex):
        self.index = index
        self.edges: dict = {}  # qualname -> [(FunctionInfo, line)]
        self.jit_entries: dict = {}  # qualname -> reason
        self.regions_by_module: dict = {}  # modname -> list[JitRegion]
        self.reachable: dict = {}  # qualname -> Reach
        self._memo: dict = {}
        self._build()

    # -------------------------------------------------------------- helpers
    def _own_calls(self, fi: FunctionInfo):
        for node in _walk_no_nested_defs(_own_statements(fi.node.body)):
            if isinstance(node, ast.Call):
                yield node

    def _func_from_expr(
        self,
        expr: ast.AST,
        mi: ModuleInfo,
        scope: Optional[FunctionInfo],
        local_fns: dict,
    ) -> Optional[FunctionInfo]:
        """A call argument that denotes a project function: a bare name, a
        factory-result local, or partial(<one of those>, ...)."""
        expr = unwrap_partial(expr)
        if isinstance(expr, ast.Name):
            if expr.id in local_fns:
                return local_fns[expr.id]
            return self.index.resolve_call(mi, expr, scope)
        return None

    def _scopes(self, mi: ModuleInfo):
        """(scope FunctionInfo|None, statement list) for module + functions."""
        yield None, mi.tree.body
        for fi in self.index.functions.values():
            if fi.modname == mi.modname and fi.path == mi.path:
                yield fi, fi.node.body

    def _local_fns(self, mi, scope, body) -> dict:
        """name -> FunctionInfo for factory-result/alias locals in a scope.

        ``raw = make_train_step(...)`` binds ``raw`` to the nested def the
        factory returns; ``f = some_fn`` aliases. Order-insensitive (a map
        over all assignments in the scope) — good enough for detection."""
        out: dict = {}
        for node in _walk_no_nested_defs(_own_statements(body)):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            value = node.value
            if isinstance(value, ast.Call):
                callee = self.index.resolve_call(mi, value.func, scope)
                if callee is not None:
                    ret = self.returns_nested(callee)
                    if ret is not None:
                        out[target.id] = ret
            elif isinstance(value, ast.Name):
                fi = self.index.resolve_call(mi, value, scope)
                if fi is not None:
                    out[target.id] = fi
        return out

    # -------------------------------------------------------------- building
    def _build(self) -> None:
        for mi in self.index.modules.values():
            self.regions_by_module[mi.modname] = build_jit_regions(mi.tree)

        # lexical entries: regions whose node is an indexed function
        for mi in self.index.modules.values():
            for region in self.regions_by_module[mi.modname]:
                fi = self.index.function_for_node(region.node)
                if fi is not None:
                    self.jit_entries.setdefault(fi.qualname, region.reason)

        # edges
        for fi in self.index.functions.values():
            mi = self.index.modules.get(fi.modname)
            if mi is None:
                continue
            edges = self.edges.setdefault(fi.qualname, [])
            for call in self._own_calls(fi):
                callee = self.index.resolve_call(mi, call.func, fi)
                if callee is not None:
                    edges.append((callee, call.lineno))
                for arg in list(call.args) + [k.value for k in call.keywords]:
                    passed = self._func_from_expr(arg, mi, fi, {})
                    if passed is not None:
                        edges.append((passed, call.lineno))

        # higher-order entries: factory results + jitted params
        for mi in self.index.modules.values():
            for scope, body in self._scopes(mi):
                self._detect_entries(mi, scope, body)

        self._compute_reachability()

    def _detect_entries(self, mi, scope, body) -> None:
        local_fns = self._local_fns(mi, scope, body)
        where = _fmt(scope) if scope else f"module scope ({mi.path})"
        for call in _walk_no_nested_defs(_own_statements(body)):
            if not isinstance(call, ast.Call):
                continue
            # direct: jax.jit(x) / lax.scan(x, ...) with x a tracked local
            if is_jit_wrapper(call.func) or is_tracing_call(call.func):
                for arg in call.args:
                    fi = self._func_from_expr(arg, mi, scope, local_fns)
                    if fi is not None:
                        self.jit_entries.setdefault(
                            fi.qualname,
                            f"passed to {dotted_name(call.func)} at "
                            f"{mi.path}:{call.lineno} in {where}",
                        )
                continue
            # higher-order: callee jits one of its params
            callee = self.index.resolve_call(mi, call.func, scope)
            if callee is None:
                continue
            jitted = self.jits_params(callee)
            if not jitted:
                continue
            bound = isinstance(call.func, ast.Attribute)
            for param, arg in callee.arg_to_param(call, bound):
                if param not in jitted:
                    continue
                fi = self._func_from_expr(arg, mi, scope, local_fns)
                if fi is not None:
                    self.jit_entries.setdefault(
                        fi.qualname,
                        f"jitted via {_fmt(callee)} (param {param!r}), "
                        f"called at {mi.path}:{call.lineno} in {where}",
                    )

    def _compute_reachability(self) -> None:
        frontier = []
        for qual, reason in self.jit_entries.items():
            fi = self.index.functions.get(qual)
            if fi is None:
                continue
            self.reachable[qual] = Reach(entry=fi, entry_reason=reason, path=())
            frontier.append(fi)
        depth = 0
        while frontier and depth < MAX_DEPTH:
            depth += 1
            nxt = []
            for fi in frontier:
                reach = self.reachable[fi.qualname]
                for callee, line in self.edges.get(fi.qualname, ()):
                    if callee.qualname in self.reachable:
                        continue
                    self.reachable[callee.qualname] = Reach(
                        entry=reach.entry,
                        entry_reason=reach.entry_reason,
                        path=reach.path + ((callee, line),),
                    )
                    nxt.append(callee)
            frontier = nxt

    # ------------------------------------------------------------- summaries
    def _memoized(self, key, compute, in_progress_value=None):
        if key in self._memo:
            return self._memo[key]
        self._memo[key] = in_progress_value  # cycle guard
        self._memo[key] = compute()
        return self._memo[key]

    def jits_params(self, fi: FunctionInfo) -> dict:
        """Param names this function hands to a jit/tracing wrapper, with
        the line it happens on: ``{param: line}``."""

        def compute():
            out = {}
            params = set(fi.params)
            for call in self._own_calls(fi):
                if not (is_jit_wrapper(call.func) or is_tracing_call(call.func)):
                    continue
                if not call.args:
                    continue
                target = unwrap_partial(call.args[0])
                if isinstance(target, ast.Name) and target.id in params:
                    out.setdefault(target.id, call.lineno)
            return out

        return self._memoized(("jits", fi.qualname), compute, {})

    def returns_nested(
        self, fi: FunctionInfo, _depth: int = 0
    ) -> Optional[FunctionInfo]:
        """The nested def this function returns (closure-factory pattern)."""
        if _depth > MAX_DEPTH:
            return None

        def compute():
            mi = self.index.modules.get(fi.modname)
            for node in _walk_no_nested_defs(_own_statements(fi.node.body)):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                value = unwrap_partial(node.value)
                if isinstance(value, ast.Name):
                    nested = self.index.functions.get(
                        f"{fi.qualname}.{value.id}"
                    )
                    if nested is not None:
                        return nested
                elif isinstance(value, ast.Call) and mi is not None:
                    callee = self.index.resolve_call(mi, value.func, fi)
                    if callee is not None and callee.qualname != fi.qualname:
                        inner = self.returns_nested(callee, _depth + 1)
                        if inner is not None:
                            return inner
            return None

        return self._memoized(("retnested", fi.qualname), compute)

    def donating_factory(self, fi: FunctionInfo, _depth: int = 0):
        """``(argnums, argnames, witness)`` when every call to this function
        yields a freshly-built donating jit (mesh.py's make_sharded_*)."""
        if _depth > MAX_DEPTH:
            return None

        def compute():
            mi = self.index.modules.get(fi.modname)
            for node in _walk_no_nested_defs(_own_statements(fi.node.body)):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                value = node.value
                if isinstance(value, ast.Call):
                    spec = donation_spec(value)
                    if spec is not None:
                        nums, names = spec
                        return (
                            nums,
                            names,
                            f"{_fmt(fi)} returns a donate_argnums jit "
                            f"(line {value.lineno})",
                        )
                    if mi is not None:
                        callee = self.index.resolve_call(mi, value.func, fi)
                        if callee is not None and callee.qualname != fi.qualname:
                            inner = self.donating_factory(callee, _depth + 1)
                            if inner is not None:
                                nums, names, witness = inner
                                return (
                                    nums,
                                    names,
                                    f"{_fmt(fi)} -> {witness}",
                                )
            return None

        return self._memoized(("donates", fi.qualname), compute)

    def collective_witness(self, fi: FunctionInfo, _depth: int = 0):
        """Call-path to a collective this function (transitively) issues,
        as a list of hop strings; None when it provably issues none we can
        see. Uniform internal guards (process_count() == 1 early-outs) do
        NOT clear it: ONE host calling this under a rank branch still posts
        the collective that the other hosts never reach."""
        if _depth > MAX_DEPTH:
            return None

        def compute():
            mi = self.index.modules.get(fi.modname)
            for call in self._own_calls(fi):
                name = dotted_name(call.func)
                if _tail(name) in _COLLECTIVE_TAILS:
                    return [f"{name} ({fi.path}:{call.lineno})"]
            if mi is None:
                return None
            for call in self._own_calls(fi):
                callee = self.index.resolve_call(mi, call.func, fi)
                if callee is None or callee.qualname == fi.qualname:
                    continue
                inner = self.collective_witness(callee, _depth + 1)
                if inner is not None:
                    return [f"{_fmt(callee)} called at line {call.lineno}"] + inner
            return None

        return self._memoized(("collective", fi.qualname), compute)

    def key_consuming_params(self, fi: FunctionInfo, _depth: int = 0) -> dict:
        """``{param: witness}`` for params whose key is (transitively)
        consumed — handed to a jax.random sampler/split, directly or through
        another project function. fold_in/clone-style DERIVATIONS don't
        count (deriving is the sanctioned way to reuse a base key)."""
        if _depth > MAX_DEPTH:
            return {}

        def compute():
            out: dict = {}
            params = set(fi.params)
            mi = self.index.modules.get(fi.modname)
            for call in self._own_calls(fi):
                name = dotted_name(call.func)
                if _is_jax_random(name):
                    if _tail(name) in _KEY_DERIVERS:
                        continue
                    for used in _names_directly_under(call):
                        if used in params and used not in out:
                            out[used] = f"{name} ({fi.path}:{call.lineno})"
                    continue
                if mi is None:
                    continue
                callee = self.index.resolve_call(mi, call.func, fi)
                if callee is None or callee.qualname == fi.qualname:
                    continue
                inner = self.key_consuming_params(callee, _depth + 1)
                if not inner:
                    continue
                bound = isinstance(call.func, ast.Attribute)
                for cparam, arg in callee.arg_to_param(call, bound):
                    if cparam not in inner:
                        continue
                    for node in ast.walk(arg):
                        if (
                            isinstance(node, ast.Name)
                            and node.id in params
                            and node.id not in out
                        ):
                            out[node.id] = (
                                f"{_fmt(callee)} called at line "
                                f"{call.lineno} -> {inner[cparam]}"
                            )
            return out

        return self._memoized(("keyparams", fi.qualname), compute, {})

    def constructs_jit(self, fi: FunctionInfo, _depth: int = 0):
        """``(line, witness)`` when EVERY call of this function builds a
        fresh jit wrapper — i.e. the construction (or an unguarded call to
        another constructor) sits outside any If/Try. A construction behind
        a cache-miss guard (harness setup_level's ``if key not in cache:``)
        is deliberate memoization and stays silent."""
        if _depth > MAX_DEPTH:
            return None

        def compute():
            mi = self.index.modules.get(fi.modname)

            def earliest_return() -> int:
                """Line of the first ``return`` in the body — an early
                return BEFORE the jit construction means some calls skip
                it (a cache lookup: serve/engine._executable), so 'every
                call constructs' does not hold."""
                lines = [
                    n.lineno
                    for n in _walk_no_nested_defs(
                        _own_statements(fi.node.body)
                    )
                    if isinstance(n, ast.Return)
                ]
                return min(lines) if lines else 10**9

            first_return = earliest_return()

            def visit(node):
                """First unguarded jit construction, pruning If/Try/IfExp
                subtrees (guarded) and nested def/lambda scopes."""
                if isinstance(
                    node,
                    (
                        ast.If,
                        ast.IfExp,
                        ast.Try,
                        ast.FunctionDef,
                        ast.AsyncFunctionDef,
                        ast.ClassDef,
                        ast.Lambda,
                    ),
                ):
                    return None
                if isinstance(node, ast.Call):
                    if is_jit_wrapper(node.func) and node.lineno <= first_return:
                        return (
                            node.lineno,
                            f"{_fmt(fi)} builds {dotted_name(node.func)} "
                            f"at line {node.lineno}",
                        )
                    if mi is not None and node.lineno <= first_return:
                        callee = self.index.resolve_call(mi, node.func, fi)
                        if callee is not None and callee.qualname != fi.qualname:
                            inner = self.constructs_jit(callee, _depth + 1)
                            if inner is not None:
                                return (node.lineno, f"{_fmt(fi)} -> {inner[1]}")
                for child in ast.iter_child_nodes(node):
                    hit = visit(child)
                    if hit is not None:
                        return hit
                return None

            for stmt in fi.node.body:
                hit = visit(stmt)
                if hit is not None:
                    return hit
            return None

        return self._memoized(("constructs", fi.qualname), compute)
