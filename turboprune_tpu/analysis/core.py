"""graftlint core: findings, waivers, the rule registry, and the driver.

A JAX codebase fails in ways generic linters never see: a stray ``.item()``
inside a jitted body silently serializes the TPU pipeline, a ``jax.jit`` in
a loop recompiles every iteration, a reused PRNG key correlates "random"
draws, and a collective under a ``process_index()`` branch deadlocks the
pod. Each of those classes has already cost this repo debugging time (see
ISSUE history: the silent no-op config in the cyclic harness, the
permutation-invariant equality check) — so the rules live here, run on
every PR, and gate via tests/test_analysis.py's self-gate instead of
relying on a reviewer to re-find them.

Design: pure stdlib ``ast`` — importing this package must never import jax
(the analyzer has to run in any environment, including pre-commit hooks on
machines with no accelerator stack). Rules are small ``ast`` visitors
registered in ``RULES``; the driver parses each file once, hands every rule
a shared :class:`ModuleContext` (source, tree, lazily-built jit-region
index), and applies inline waivers afterwards so waived findings still
appear in reports (auditable, not invisible).

Waiver syntax, checked by tests/test_analysis.py::test_waiver_*::

    x = bad_thing()  # graftlint: disable=rule-id[,other-rule] -- reason

A waiver comment alone on a line applies to the next code line (for sites
where the waived statement is long). The reason text is optional to the
parser but required by convention: it doubles as documentation of WHY the
site is exempt, and reviewers should reject reason-less waivers.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

__all__ = [
    "Finding",
    "Waiver",
    "Rule",
    "RULES",
    "register",
    "ModuleContext",
    "AnalysisResult",
    "analyze_source",
    "analyze_files",
    "analyze_paths",
    "analyze_project",
    "is_test_file",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``trace`` (project mode) is the call-path from a jit entry / consuming
    helper / collective sink to the flagged site, one hop per string —
    present so a reviewer can audit an interprocedural finding (or its
    waiver) without re-deriving the chain by hand."""

    file: str
    line: int
    col: int
    rule: str
    severity: str  # "error" | "warning"
    message: str
    waived: bool = False
    waiver_reason: Optional[str] = None
    trace: Optional[list] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Waiver:
    """A parsed ``# graftlint: disable=...`` comment."""

    file: str
    line: int  # line the comment sits on
    rules: frozenset
    reason: Optional[str]
    applies_to: int  # line whose findings it waives
    used: bool = False

    def as_dict(self) -> dict:
        return {
            "file": self.file,
            "line": self.line,
            "rules": sorted(self.rules),
            "reason": self.reason,
            "applies_to": self.applies_to,
            "used": self.used,
        }


_WAIVER_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(.*\S))?\s*$"
)


def parse_waivers(source: str, file: str) -> list:
    """Extract waivers via the tokenizer (a ``#`` inside a string literal is
    not a comment). A comment-only line waives the NEXT code line."""
    comments: list[tuple[int, str, bool]] = []  # (line, text, standalone)
    code_lines: set[int] = set()
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            standalone = tok.line.strip().startswith("#")
            comments.append((tok.start[0], tok.string, standalone))
        elif tok.type not in (
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENDMARKER,
        ):
            for ln in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(ln)

    waivers = []
    for line, text, standalone in comments:
        m = _WAIVER_RE.search(text)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(","))
        if standalone:
            later = [ln for ln in code_lines if ln > line]
            applies_to = min(later) if later else line
        else:
            applies_to = line
        waivers.append(
            Waiver(
                file=file,
                line=line,
                rules=rules,
                reason=m.group(2),
                applies_to=applies_to,
            )
        )
    return waivers


def is_test_file(path) -> bool:
    """Test files get a few deliberately looser rules (``skip_in_tests``):
    tests construct throwaway jits and fixed PRNG keys on purpose."""
    p = Path(path)
    if any(part == "tests" for part in p.parts):
        return True
    return p.name.startswith("test_") or p.name == "conftest.py"


class ModuleContext:
    """Everything a rule needs about one file, parsed once."""

    def __init__(self, path, source: str):
        self.path = str(path)
        self.source = source
        self.tree = ast.parse(source)  # caller handles SyntaxError
        self.is_test = is_test_file(path)
        self._regions = None

    @property
    def jit_regions(self):
        """Lazily-built lexical jit/trace region index (regions.py)."""
        if self._regions is None:
            from .regions import build_jit_regions

            self._regions = build_jit_regions(self.tree)
        return self._regions

    def finding(
        self,
        rule: "Rule",
        node: ast.AST,
        message: str,
        trace: Optional[list] = None,
    ) -> Finding:
        return Finding(
            file=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule=rule.id,
            severity=rule.severity,
            message=message,
            trace=trace,
        )


class Rule:
    """Base class: subclass, set ``id``/``severity``/``description``,
    implement ``check``, and decorate with :func:`register`."""

    id: str = ""
    severity: str = "error"
    description: str = ""
    # True: rule does not run on tests/conftest files (see is_test_file).
    skip_in_tests: bool = False
    # True: rule needs the project layer (symbol table / thread model) and
    # fires only from check_project; the per-file driver skips it and
    # per-file stale-waiver accounting treats its waivers as out of scope.
    project_only: bool = False
    # Why the hazard matters on TPU — the third column of the README rule
    # catalog, which `graftlint --rule-docs` generates from this registry.
    doc_why: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError


RULES: dict = {}


def register(cls):
    rule = cls()
    assert rule.id and rule.id not in RULES, f"bad rule id {rule.id!r}"
    RULES[rule.id] = rule
    return cls


@dataclasses.dataclass
class AnalysisResult:
    findings: list  # every Finding, waived ones flagged
    waivers: list  # every Waiver, used ones flagged
    files_analyzed: int
    # True for analyze_project results; per-file results leave it False so
    # stale accounting can scope waivers to the rules the mode can fire.
    project: bool = False

    @property
    def unwaived(self) -> list:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> list:
        return [f for f in self.findings if f.waived]

    @property
    def unused_waivers(self) -> list:
        """Waivers that matched nothing. In per-file mode (including
        ``--changed``), waivers naming only project-scope rules are out of
        scope — they CANNOT match there and only project mode may call
        them stale (which the project self-gate does). The project-only
        set is derived from the conf-rule registry (plus the ``conf-``
        prefix as a guard for rules not yet registered), so a new conf
        rule never reintroduces the false-stale bug by omission."""
        unused = [w for w in self.waivers if not w.used]
        if self.project:
            return unused
        from .conf_rules import CONF_RULES  # lazy: conf_rules imports core

        project_only = set(CONF_RULES) | {
            rid for rid, r in RULES.items() if r.project_only
        }
        return [
            w
            for w in unused
            if not all(
                r in project_only or r.startswith("conf-") for r in w.rules
            )
        ]


def _apply_waivers(
    findings: list, waivers: list
) -> list:
    by_line: dict[int, list] = {}
    for w in waivers:
        by_line.setdefault(w.applies_to, []).append(w)
    out = []
    for f in findings:
        hit = None
        for w in by_line.get(f.line, ()):
            if f.rule in w.rules:
                hit = w
                break
        if hit is not None:
            hit.used = True
            out.append(
                dataclasses.replace(f, waived=True, waiver_reason=hit.reason)
            )
        else:
            out.append(f)
    return out


def _parse_error_finding(file: str, e: SyntaxError) -> Finding:
    return Finding(
        file=file,
        line=e.lineno or 1,
        col=(e.offset or 1) - 1,
        rule="parse-error",
        severity="error",
        message=f"file does not parse: {e.msg}",
    )


def _run_rules_dedup(ctx: ModuleContext, select=None) -> list:
    """Per-file rules over one parsed module, exact duplicates collapsed
    (nested jit regions can surface the same node twice)."""
    findings = []
    for rule in RULES.values():
        if rule.project_only:
            continue  # fires from check_project, never per-file
        if select and rule.id not in select:
            continue
        if rule.skip_in_tests and ctx.is_test:
            continue
        findings.extend(rule.check(ctx))
    seen: set = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        key = (f.line, f.col, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def analyze_source(
    source: str,
    path="<string>",
    select: Optional[Sequence[str]] = None,
) -> tuple:
    """Run every (selected) rule over one module. Returns
    ``(findings, waivers)`` with waivers already applied."""
    file = str(path)
    waivers = parse_waivers(source, file)
    try:
        ctx = ModuleContext(file, source)
    except SyntaxError as e:
        return _apply_waivers([_parse_error_finding(file, e)], waivers), waivers
    return _apply_waivers(_run_rules_dedup(ctx, select), waivers), waivers


def iter_python_files(paths: Iterable) -> list:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            files.append(p)
        else:
            raise FileNotFoundError(f"not a .py file or directory: {p}")
    return files


def analyze_paths(
    paths: Iterable,
    select: Optional[Sequence[str]] = None,
) -> AnalysisResult:
    """Analyze every ``.py`` under ``paths`` (files or directories)."""
    all_findings: list = []
    all_waivers: list = []
    files = iter_python_files(paths)
    for f in files:
        findings, waivers = analyze_source(
            f.read_text(encoding="utf-8"), f, select=select
        )
        all_findings.extend(findings)
        all_waivers.extend(waivers)
    all_findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return AnalysisResult(
        findings=all_findings,
        waivers=all_waivers,
        files_analyzed=len(files),
    )


def _conf_root_for(path: Path) -> Path:
    """Best-effort conf root for a yaml analyzed WITHOUT project context:
    the tree up to (and including) the last ``conf`` path component, so
    group-shaped paths still resolve; else the file's directory."""
    parts = path.parts
    if "conf" in parts[:-1]:
        idx = max(i for i, c in enumerate(parts[:-1]) if c == "conf")
        return Path(*parts[: idx + 1])
    return path.parent


def analyze_files(
    files: Iterable,
    select: Optional[Sequence[str]] = None,
) -> AnalysisResult:
    """Per-file mode over an explicit mixed list of ``.py`` and
    ``.yaml``/``.yml`` files (the ``--changed`` surface): Python files get
    the per-file rules; yaml files get the schema-independent conf checks
    (parse errors, duplicate keys, defaults shape — no project symbol
    table, so the schema cross-checks stay project mode's job)."""
    from .conf_rules import analyze_conf

    py_files: list = []
    yaml_files: list = []
    for f in files:
        p = Path(f)
        if p.suffix == ".py":
            py_files.append(p)
        elif p.suffix in (".yaml", ".yml"):
            yaml_files.append((p, _conf_root_for(p)))
        else:
            raise FileNotFoundError(f"not a .py/.yaml file: {p}")
    all_findings: list = []
    all_waivers: list = []
    for f in py_files:
        findings, waivers = analyze_source(
            f.read_text(encoding="utf-8"), f, select=select
        )
        all_findings.extend(findings)
        all_waivers.extend(waivers)
    if yaml_files:
        conf_findings, conf_waivers = analyze_conf(yaml_files, {})
        conf_findings = [
            f for f in conf_findings if not select or f.rule in select
        ]
        all_findings.extend(
            _apply_waivers_by_file(conf_findings, conf_waivers)
        )
        all_waivers.extend(conf_waivers)
    all_findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return AnalysisResult(
        findings=all_findings,
        waivers=all_waivers,
        files_analyzed=len(py_files) + len(yaml_files),
    )


# ----------------------------------------------------------- project mode


def _yaml_root(file: Path, root: Path) -> Path:
    """The conf root for one yaml: the passed directory, advanced through
    a leading ``conf`` component so ``<repo>/conf/<group>/<option>.yaml``
    resolves its group whether the caller passed the repo root or conf/
    itself."""
    try:
        rel = file.relative_to(root)
    except ValueError:
        return root
    while rel.parts and rel.parts[0] == "conf":
        root = root / "conf"
        rel = file.relative_to(root)
    return root


def _collect_project_files(paths) -> tuple:
    """``(py_files, [(yaml_file, conf_root), ...])`` under ``paths``.

    A directory contributes its ``.py`` tree to the symbol table and its
    ``.yaml``/``.yml`` tree to the config rules. Overlapping paths dedupe
    (deepest conf root wins, so group resolution stays correct)."""
    py_files: list = []
    yaml_roots: dict = {}
    for p in paths:
        p = Path(p)
        if p.is_dir():
            py_files.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
            for pattern in ("*.yaml", "*.yml"):
                for f in sorted(p.rglob(pattern)):
                    root = _yaml_root(f, p)
                    key = f.resolve()
                    prior = yaml_roots.get(key)
                    if prior is None or len(str(root)) > len(str(prior[1])):
                        yaml_roots[key] = (f, root)
        elif p.suffix == ".py":
            py_files.append(p)
        elif p.suffix in (".yaml", ".yml"):
            yaml_roots.setdefault(p.resolve(), (p, p.parent))
        else:
            raise FileNotFoundError(
                f"not a .py/.yaml file or directory: {p}"
            )
    seen_py: set = set()
    unique_py: list = []
    for f in py_files:
        key = Path(f).resolve()
        if key not in seen_py:
            seen_py.add(key)
            unique_py.append(f)
    return unique_py, sorted(yaml_roots.values(), key=lambda t: str(t[0]))


def _apply_waivers_by_file(findings: list, waivers: list) -> list:
    by_file: dict = {}
    for w in waivers:
        by_file.setdefault(w.file, []).append(w)
    grouped: dict = {}
    for f in findings:
        grouped.setdefault(f.file, []).append(f)
    out: list = []
    for file, fs in grouped.items():
        out.extend(_apply_waivers(fs, by_file.get(file, [])))
    return out


def _project_file_scan(args) -> tuple:
    """Process-pool worker: parse one file and run the per-file rules.

    Returns ``(file, source, findings, waivers, parsed)``. Module-level
    (picklable) on purpose; the lazy imports re-register the rule set when
    the pool uses the spawn start method (fork inherits it)."""
    path, select = args
    from . import concurrency_rules, dtype_rules, rules, shape_rules  # noqa: F401

    p = Path(path)
    source = p.read_text(encoding="utf-8")
    file = str(p)
    waivers = parse_waivers(source, file)
    try:
        ctx = ModuleContext(file, source)
    except SyntaxError as e:
        return file, source, [_parse_error_finding(file, e)], waivers, False
    return file, source, _run_rules_dedup(ctx, select), waivers, True


# Below this, process-pool startup dominates: run serial.
_MIN_PARALLEL_FILES = 8


def _scan_project_files(py_files, select, jobs) -> list:
    """Per-file scans for project mode, parallel when it pays.

    Output order equals input order either way (``Executor.map`` preserves
    it), and the driver's final sort makes finding order deterministic, so
    ``--jobs`` can never change what check.sh diffs. Pool failures
    (sandboxes without semaphores, missing /dev/shm) fall back to serial."""
    if jobs is None or jobs <= 0:
        jobs = os.cpu_count() or 1
    args = [(str(f), tuple(select) if select else None) for f in py_files]
    if jobs > 1 and len(args) >= _MIN_PARALLEL_FILES:
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor

        try:
            # Spawn, not fork: analyze_project is also called from inside
            # test processes that have already imported jax (multithreaded
            # — forking it can deadlock the child). _project_file_scan
            # lazy-imports the rule modules precisely so spawned workers
            # can bootstrap from an empty interpreter.
            with ProcessPoolExecutor(
                max_workers=jobs,
                mp_context=multiprocessing.get_context("spawn"),
            ) as pool:
                return list(pool.map(_project_file_scan, args, chunksize=4))
        except (OSError, PermissionError, ImportError):
            pass
    return [_project_file_scan(a) for a in args]


def analyze_project(
    paths: Iterable,
    select: Optional[Sequence[str]] = None,
    jobs: Optional[int] = None,
) -> AnalysisResult:
    """Whole-project mode: per-file rules PLUS the interprocedural layer
    (symbol table + call graph; rules fire through call chains with a
    call-path trace) PLUS the config static analysis over ``*.yaml`` files
    against the schema dataclasses. Waivers come from Python comments and
    from ``# graftlint: disable=...`` YAML comments alike; stale-waiver
    accounting spans both layers (this is the mode the pre-PR gate runs).

    ``jobs`` widens the per-file half across a process pool (None/0 =
    one per CPU, 1 = serial); the interprocedural layer stays in-process
    on a re-parse of the same sources."""
    from .conf_rules import analyze_conf
    from .interproc import check_project
    from .project import ProjectIndex

    py_files, yaml_files = _collect_project_files(paths)
    raw_findings: list = []
    all_waivers: list = []
    contexts: dict = {}
    for file, source, findings, waivers, parsed in _scan_project_files(
        py_files, select, jobs
    ):
        all_waivers.extend(waivers)
        raw_findings.extend(findings)
        if parsed:
            contexts[file] = ModuleContext(file, source)

    # interprocedural layer (dedup: a site already flagged per-file keeps
    # its per-file finding; the interprocedural twin is dropped)
    index = ProjectIndex.build(contexts.values())
    seen = {(f.file, f.line, f.rule) for f in raw_findings}
    for f in check_project(index, contexts):
        if select and f.rule not in select:
            continue
        if (f.file, f.line, f.rule) not in seen:
            seen.add((f.file, f.line, f.rule))
            raw_findings.append(f)

    # config rules
    conf_findings, conf_waivers = analyze_conf(yaml_files, contexts)
    raw_findings.extend(
        f for f in conf_findings if not select or f.rule in select
    )
    all_waivers.extend(conf_waivers)

    findings = _apply_waivers_by_file(raw_findings, all_waivers)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return AnalysisResult(
        findings=findings,
        waivers=all_waivers,
        files_analyzed=len(py_files) + len(yaml_files),
        project=True,
    )
