"""graftlint core: findings, waivers, the rule registry, and the driver.

A JAX codebase fails in ways generic linters never see: a stray ``.item()``
inside a jitted body silently serializes the TPU pipeline, a ``jax.jit`` in
a loop recompiles every iteration, a reused PRNG key correlates "random"
draws, and a collective under a ``process_index()`` branch deadlocks the
pod. Each of those classes has already cost this repo debugging time (see
ISSUE history: the silent no-op config in the cyclic harness, the
permutation-invariant equality check) — so the rules live here, run on
every PR, and gate via tests/test_analysis.py's self-gate instead of
relying on a reviewer to re-find them.

Design: pure stdlib ``ast`` — importing this package must never import jax
(the analyzer has to run in any environment, including pre-commit hooks on
machines with no accelerator stack). Rules are small ``ast`` visitors
registered in ``RULES``; the driver parses each file once, hands every rule
a shared :class:`ModuleContext` (source, tree, lazily-built jit-region
index), and applies inline waivers afterwards so waived findings still
appear in reports (auditable, not invisible).

Waiver syntax, checked by tests/test_analysis.py::test_waiver_*::

    x = bad_thing()  # graftlint: disable=rule-id[,other-rule] -- reason

A waiver comment alone on a line applies to the next code line (for sites
where the waived statement is long). The reason text is optional to the
parser but required by convention: it doubles as documentation of WHY the
site is exempt, and reviewers should reject reason-less waivers.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

__all__ = [
    "Finding",
    "Waiver",
    "Rule",
    "RULES",
    "register",
    "ModuleContext",
    "AnalysisResult",
    "analyze_source",
    "analyze_paths",
    "is_test_file",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    file: str
    line: int
    col: int
    rule: str
    severity: str  # "error" | "warning"
    message: str
    waived: bool = False
    waiver_reason: Optional[str] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Waiver:
    """A parsed ``# graftlint: disable=...`` comment."""

    file: str
    line: int  # line the comment sits on
    rules: frozenset
    reason: Optional[str]
    applies_to: int  # line whose findings it waives
    used: bool = False

    def as_dict(self) -> dict:
        return {
            "file": self.file,
            "line": self.line,
            "rules": sorted(self.rules),
            "reason": self.reason,
            "applies_to": self.applies_to,
            "used": self.used,
        }


_WAIVER_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(.*\S))?\s*$"
)


def parse_waivers(source: str, file: str) -> list:
    """Extract waivers via the tokenizer (a ``#`` inside a string literal is
    not a comment). A comment-only line waives the NEXT code line."""
    comments: list[tuple[int, str, bool]] = []  # (line, text, standalone)
    code_lines: set[int] = set()
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            standalone = tok.line.strip().startswith("#")
            comments.append((tok.start[0], tok.string, standalone))
        elif tok.type not in (
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENDMARKER,
        ):
            for ln in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(ln)

    waivers = []
    for line, text, standalone in comments:
        m = _WAIVER_RE.search(text)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(","))
        if standalone:
            later = [ln for ln in code_lines if ln > line]
            applies_to = min(later) if later else line
        else:
            applies_to = line
        waivers.append(
            Waiver(
                file=file,
                line=line,
                rules=rules,
                reason=m.group(2),
                applies_to=applies_to,
            )
        )
    return waivers


def is_test_file(path) -> bool:
    """Test files get a few deliberately looser rules (``skip_in_tests``):
    tests construct throwaway jits and fixed PRNG keys on purpose."""
    p = Path(path)
    if any(part == "tests" for part in p.parts):
        return True
    return p.name.startswith("test_") or p.name == "conftest.py"


class ModuleContext:
    """Everything a rule needs about one file, parsed once."""

    def __init__(self, path, source: str):
        self.path = str(path)
        self.source = source
        self.tree = ast.parse(source)  # caller handles SyntaxError
        self.is_test = is_test_file(path)
        self._regions = None

    @property
    def jit_regions(self):
        """Lazily-built lexical jit/trace region index (regions.py)."""
        if self._regions is None:
            from .regions import build_jit_regions

            self._regions = build_jit_regions(self.tree)
        return self._regions

    def finding(
        self, rule: "Rule", node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            file=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule=rule.id,
            severity=rule.severity,
            message=message,
        )


class Rule:
    """Base class: subclass, set ``id``/``severity``/``description``,
    implement ``check``, and decorate with :func:`register`."""

    id: str = ""
    severity: str = "error"
    description: str = ""
    # True: rule does not run on tests/conftest files (see is_test_file).
    skip_in_tests: bool = False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError


RULES: dict = {}


def register(cls):
    rule = cls()
    assert rule.id and rule.id not in RULES, f"bad rule id {rule.id!r}"
    RULES[rule.id] = rule
    return cls


@dataclasses.dataclass
class AnalysisResult:
    findings: list  # every Finding, waived ones flagged
    waivers: list  # every Waiver, used ones flagged
    files_analyzed: int

    @property
    def unwaived(self) -> list:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> list:
        return [f for f in self.findings if f.waived]

    @property
    def unused_waivers(self) -> list:
        return [w for w in self.waivers if not w.used]


def _apply_waivers(
    findings: list, waivers: list
) -> list:
    by_line: dict[int, list] = {}
    for w in waivers:
        by_line.setdefault(w.applies_to, []).append(w)
    out = []
    for f in findings:
        hit = None
        for w in by_line.get(f.line, ()):
            if f.rule in w.rules:
                hit = w
                break
        if hit is not None:
            hit.used = True
            out.append(
                dataclasses.replace(f, waived=True, waiver_reason=hit.reason)
            )
        else:
            out.append(f)
    return out


def analyze_source(
    source: str,
    path="<string>",
    select: Optional[Sequence[str]] = None,
) -> tuple:
    """Run every (selected) rule over one module. Returns
    ``(findings, waivers)`` with waivers already applied."""
    file = str(path)
    waivers = parse_waivers(source, file)
    try:
        ctx = ModuleContext(file, source)
    except SyntaxError as e:
        findings = [
            Finding(
                file=file,
                line=e.lineno or 1,
                col=(e.offset or 1) - 1,
                rule="parse-error",
                severity="error",
                message=f"file does not parse: {e.msg}",
            )
        ]
        return _apply_waivers(findings, waivers), waivers

    findings = []
    for rule in RULES.values():
        if select and rule.id not in select:
            continue
        if rule.skip_in_tests and ctx.is_test:
            continue
        findings.extend(rule.check(ctx))
    # Nested jit regions (a scan body inside a jitted def) can surface the
    # same node twice — collapse exact duplicates.
    seen: set = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        key = (f.line, f.col, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return _apply_waivers(unique, waivers), waivers


def iter_python_files(paths: Iterable) -> list:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            files.append(p)
        else:
            raise FileNotFoundError(f"not a .py file or directory: {p}")
    return files


def analyze_paths(
    paths: Iterable,
    select: Optional[Sequence[str]] = None,
) -> AnalysisResult:
    """Analyze every ``.py`` under ``paths`` (files or directories)."""
    all_findings: list = []
    all_waivers: list = []
    files = iter_python_files(paths)
    for f in files:
        findings, waivers = analyze_source(
            f.read_text(encoding="utf-8"), f, select=select
        )
        all_findings.extend(findings)
        all_waivers.extend(waivers)
    all_findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return AnalysisResult(
        findings=all_findings,
        waivers=all_waivers,
        files_analyzed=len(files),
    )
