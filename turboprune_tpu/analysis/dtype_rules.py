"""Dtype-flow rules: the static half of ROADMAP item 6's bf16 guardrail.

Four rules over the lattice/interpreter in dtype_flow.py, all tuned to the
ways JAX silently re-promotes a reduced-precision path to f32:

* ``silent-upcast`` — inside a reduced-precision jit region (declared
  ``# graftlint: dtype-policy=bf16`` or lexically marked with bf16 casts),
  arithmetic that mixes a reduced operand with a strongly-typed f32/f64
  one, ``np.*`` compute on traced values (float64 on host), default-dtype
  ``jnp.mean``/``var``/``std``/``softmax`` accumulation, and Python float
  literals hardening integer operands to f32.
* ``weak-type-promotion`` — the same traced parameter of a jitted callable
  receiving a Python int literal at one call site and a float literal at
  another: the weak scalar hardens to i32 vs f32 across the jit boundary,
  which is a dtype flip and a silent recompile the retrace-hazard rule
  (which only sees jit CONSTRUCTION) cannot catch.
* ``scan-carry-dtype-drift`` — ``lax.scan`` call sites where the inferred
  init dtype differs from the dtype the body returns for the carry slot.
  XLA either raises at trace time or, for weakly-typed drifts, re-promotes
  per iteration. Bodies resolve through ``functools.partial`` (bound
  leading args skipped) and closures, matching regions.py.
* ``missing-preferred-element-type`` — matmul/conv-family calls on reduced
  operands without an explicit accumulation dtype; the in-repo idiom is
  ``lax.dot_general(..., preferred_element_type=jnp.float32)``
  (ops/flash.py).

In project mode the first and last rules also fire through call chains: a
helper reachable from a reduced jit entry is analyzed with its params
seeded to the entry's reduced dtype, and findings carry the call-path
trace, same shape as interproc.py's. All four rules skip test files —
tests mix dtypes on purpose — and only fire when the lattice KNOWS both
sides of a hazard, so ``unknown`` stays silent rather than noisy.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterator, Optional

from .core import ModuleContext, Rule, register
from .dtype_flow import (
    BF16,
    F32,
    F64,
    INT,
    REDUCED,
    UNKNOWN,
    WEAK_FLOAT,
    WEAK_INT,
    DtypePolicies,
    ScopeDtypes,
    join,
    parse_dtype_policies,
    region_reduced,
)
from .regions import (
    dotted_name,
    is_jit_wrapper,
    is_tracing_call,
    param_names,
    partial_bindings,
)

__all__ = [
    "SilentUpcastRule",
    "WeakTypePromotionRule",
    "ScanCarryDtypeDriftRule",
    "MissingPreferredElementTypeRule",
    "dtype_project_findings",
]


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _root(name: Optional[str]) -> Optional[str]:
    return name.split(".", 1)[0] if name else None


def _policies(ctx: ModuleContext) -> DtypePolicies:
    cached = getattr(ctx, "_dtype_policies", None)
    if cached is None:
        cached = parse_dtype_policies(ctx.source, ctx.tree)
        ctx._dtype_policies = cached
    return cached


def _reduced_regions(ctx: ModuleContext) -> Iterator:
    """(region, dtype, why, ScopeDtypes) for each reduced-precision jit
    region — traced params seeded to the region's reduced dtype so flow
    starts from the declared inputs."""
    pol = _policies(ctx)
    for region in ctx.jit_regions:
        red = region_reduced(region, pol)
        if red is None:
            continue
        dt, why = red
        seed = {p: dt for p in region.traced_params}
        yield region, dt, why, ScopeDtypes(region.node, seed=seed)


# --------------------------------------------------------- silent-upcast

_NP_ROOTS = {"np", "numpy", "onp"}
# host-pull tails are jit-host-sync's finding already; don't double-report
_NP_PULL_TAILS = {"array", "asarray", "asanyarray", "frombuffer", "copy"}
# np dtype constructors are an EXPLICIT dtype choice, not a silent one
_NP_CTOR_TAILS = {
    "float16", "float32", "float64", "half", "single", "double",
    "int8", "int16", "int32", "int64", "uint8", "uint32", "bool_",
}
_ACCUM_TAILS = {"mean", "var", "std", "softmax", "log_softmax"}
_ARITH_OPS = (
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
    ast.Pow, ast.MatMult,
)


def _is_jnp_like(name: Optional[str]) -> bool:
    if not name:
        return False
    return (
        _root(name) in ("jnp", "nn")
        or name.startswith("jax.numpy.")
        or name.startswith("jax.nn.")
    )


def _upcast_scan(
    rule: Rule,
    ctx: ModuleContext,
    root: ast.AST,
    sd: ScopeDtypes,
    why: str,
    trace_fn: Optional[Callable] = None,
) -> Iterator:
    for node in ast.walk(root):
        if not isinstance(node, (ast.BinOp, ast.Call)):
            continue
        trace = trace_fn(node) if trace_fn else None
        if isinstance(node, ast.BinOp) and isinstance(node.op, _ARITH_OPS):
            l, r = sd.dtype_of(node.left), sd.dtype_of(node.right)
            pair = {l, r}
            if (pair & REDUCED) and (pair & {F32, F64}):
                yield ctx.finding(
                    rule,
                    node,
                    f"arithmetic mixes {l} and {r}: the reduced operand "
                    f"silently promotes to {join(l, r)} and the bf16 "
                    f"speedup is lost (reduced-precision context: {why}); "
                    "cast one operand explicitly so the promotion is a "
                    "decision, not an accident",
                    trace=trace,
                )
            elif (
                WEAK_FLOAT in pair
                and INT in pair
                and (
                    isinstance(node.left, ast.Constant)
                    or isinstance(node.right, ast.Constant)
                )
            ):
                yield ctx.finding(
                    rule,
                    node,
                    "Python float literal in arithmetic with an integer "
                    "traced value hardens to f32 (reduced-precision "
                    f"context: {why}); use jnp.asarray(literal, dtype) or "
                    "an integer literal",
                    trace=trace,
                )
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            tail = _tail(name)
            if (
                _root(name) in _NP_ROOTS
                and tail not in _NP_PULL_TAILS
                and tail not in _NP_CTOR_TAILS
                and any(
                    sd.dtype_of(a) in (REDUCED | {F32, INT})
                    for a in node.args
                )
            ):
                yield ctx.finding(
                    rule,
                    node,
                    f"{name}(...) on a traced value computes on host in "
                    "float64 — a silent upcast AND a device sync "
                    f"(reduced-precision context: {why}); use the jnp "
                    "equivalent with an explicit dtype",
                    trace=trace,
                )
            elif (
                _is_jnp_like(name)
                and tail in _ACCUM_TAILS
                and not any(
                    kw.arg in ("dtype", "preferred_element_type")
                    for kw in node.keywords
                )
                and node.args
                and sd.dtype_of(node.args[0]) in REDUCED
            ):
                d = sd.dtype_of(node.args[0])
                yield ctx.finding(
                    rule,
                    node,
                    f"{name}(...) accumulates in {d} with no explicit "
                    f"accumulation dtype (reduced-precision context: {why})"
                    " — long reductions lose mass in bf16; pass "
                    "dtype=jnp.float32 (or upcast the operand explicitly)",
                    trace=trace,
                )


@register
class SilentUpcastRule(Rule):
    id = "silent-upcast"
    severity = "warning"
    skip_in_tests = True
    description = (
        "fp32-promoting op (strong-f32 operand mix, np.* on traced values, "
        "default-dtype mean/var/softmax accumulation) inside a "
        "reduced-precision jit region"
    )
    doc_why = (
        "each silent promotion quietly runs that op at fp32 — the bf16 "
        "speedup evaporates one line at a time, with "
        "bit-identical-looking code"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        for region, _dt, why, sd in _reduced_regions(ctx):
            yield from _upcast_scan(self, ctx, region.node, sd, why)


# -------------------------------------------------- weak-type-promotion


def _weak_literal_class(arg: ast.AST) -> Optional[str]:
    if isinstance(arg, ast.UnaryOp) and isinstance(
        arg.op, (ast.USub, ast.UAdd)
    ):
        arg = arg.operand
    if isinstance(arg, ast.Constant) and not isinstance(arg.value, bool):
        if isinstance(arg.value, int):
            return "int"
        if isinstance(arg.value, float):
            return "float"
    return None


def _static_names(call: ast.Call) -> set:
    from .regions import literal_str_seq

    for kw in call.keywords:
        if kw.arg == "static_argnames":
            return set(literal_str_seq(kw.value) or ())
    return set()


@register
class WeakTypePromotionRule(Rule):
    id = "weak-type-promotion"
    severity = "warning"
    skip_in_tests = True
    description = (
        "same traced param of a jitted callable gets a Python int literal "
        "at one site and a float literal at another — the weak scalar "
        "hardens to different dtypes across the jit boundary (silent "
        "recompile per flip)"
    )
    doc_why = (
        "the weak scalar hardens to i32 vs f32 across the jit boundary — "
        "a dtype flip and a silent recompile per flip"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        # jitted callables visible in this module, by the name calls use
        jitted: dict = {}  # callable name -> (positional params, traced set)
        defs = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for region in ctx.jit_regions:
            node = region.node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and region.reason.startswith("@"):
                jitted[node.name] = (param_names(node), region.traced_params)
        for node in ast.walk(ctx.tree):
            # g = jax.jit(f, ...): calls to g cross the boundary
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and is_jit_wrapper(node.value.func)
                and node.value.args
                and isinstance(node.value.args[0], ast.Name)
                and node.value.args[0].id in defs
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                fn = defs[node.value.args[0].id]
                static = _static_names(node.value)
                params = param_names(fn)
                jitted[node.targets[0].id] = (
                    params,
                    frozenset(p for p in params if p not in static),
                )

        if not jitted:
            return
        sites: dict = {}  # (callable, param) -> {class: first call node}
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                continue
            params, traced = jitted[node.func.id]
            bindings = list(zip(params, node.args)) + [
                (kw.arg, kw.value) for kw in node.keywords if kw.arg
            ]
            for param, arg in bindings:
                if param not in traced:
                    continue
                cls = _weak_literal_class(arg)
                if cls is None:
                    continue
                sites.setdefault((node.func.id, param), {}).setdefault(
                    cls, node
                )
        for (fname, param), by_class in sites.items():
            if "int" in by_class and "float" in by_class:
                first, second = sorted(
                    (by_class["int"], by_class["float"]),
                    key=lambda n: (n.lineno, n.col_offset),
                )
                yield ctx.finding(
                    self,
                    second,
                    f"jitted {fname}() takes a Python int for traced param "
                    f"{param!r} at line {first.lineno} and a float here — "
                    "the weak scalar hardens to i32 vs f32 across the jit "
                    "boundary, so each flip recompiles silently; pass "
                    "jnp.asarray(v, dtype) or make the literals agree",
                )


# ------------------------------------------------ scan-carry-dtype-drift


def _harden(d: str) -> str:
    if d == WEAK_FLOAT:
        return F32
    if d == WEAK_INT:
        return INT
    return d


@register
class ScanCarryDtypeDriftRule(Rule):
    id = "scan-carry-dtype-drift"
    severity = "error"
    skip_in_tests = True
    description = (
        "lax.scan carry-in dtype differs from the dtype the body returns "
        "for the carry slot (trace error or per-iteration re-promotion)"
    )
    doc_why = (
        "XLA raises at trace time, or for weak drifts re-promotes every "
        "iteration of the epoch-length scan"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        defs = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        sd = ScopeDtypes(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and is_tracing_call(node.func)
                and _tail(dotted_name(node.func)) == "scan"
                and len(node.args) >= 2
            ):
                continue
            d_in = _harden(sd.dtype_of(node.args[1]))
            if d_in == UNKNOWN:
                continue
            body, n_bound, _kw_bound = partial_bindings(node.args[0])
            if isinstance(body, ast.Name):
                body = defs.get(body.id)
            if not isinstance(
                body, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            positional = [
                p.arg for p in body.args.posonlyargs + body.args.args
            ]
            if n_bound >= len(positional):
                continue
            carry = positional[n_bound]
            body_sd = ScopeDtypes(body, seed={carry: d_in})
            for ret, _d in body_sd.returns:
                val = ret.value if isinstance(ret, ast.Return) else ret
                if not (isinstance(val, ast.Tuple) and val.elts):
                    continue
                d_out = body_sd.dtype_of(val.elts[0])
                if d_out in (UNKNOWN, WEAK_FLOAT, WEAK_INT):
                    continue  # weak carries adopt the init dtype
                if d_out != d_in:
                    body_name = getattr(body, "name", "<lambda>")
                    yield ctx.finding(
                        self,
                        node,
                        f"lax.scan carry enters as {d_in} but body "
                        f"{body_name}() returns {d_out} for the carry slot "
                        "— carry-in and carry-out dtypes must match "
                        "exactly; cast the returned carry back (or fix the "
                        "init dtype)",
                    )
                    break


# ------------------------------------- missing-preferred-element-type

_MATMUL_TAILS = {"matmul", "dot", "tensordot", "einsum"}
_LAX_MATMUL_TAILS = {"dot_general", "conv_general_dilated", "conv"}


def _pet_scan(
    rule: Rule,
    ctx: ModuleContext,
    root: ast.AST,
    sd: ScopeDtypes,
    why: str,
    trace_fn: Optional[Callable] = None,
) -> Iterator:
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        tail = _tail(name)
        if tail in _MATMUL_TAILS:
            if not (_is_jnp_like(name) or _root(name) == "lax"):
                continue
        elif tail in _LAX_MATMUL_TAILS:
            if not (name and "lax" in name.split(".")):
                continue
        else:
            continue
        if any(kw.arg == "preferred_element_type" for kw in node.keywords):
            continue
        operands = node.args
        if tail == "einsum" and operands and isinstance(operands[0], ast.Constant):
            operands = operands[1:]
        hits = [sd.dtype_of(a) for a in operands if sd.dtype_of(a) in REDUCED]
        if not hits:
            continue
        yield ctx.finding(
            rule,
            node,
            f"{name}(...) on {hits[0]} operands without "
            "preferred_element_type — the MXU accumulates in f32 but the "
            f"result truncates back to {hits[0]} per tile "
            f"(reduced-precision context: {why}); pass "
            "preferred_element_type=jnp.float32 (pattern: ops/flash.py)",
            trace=trace_fn(node) if trace_fn else None,
        )


@register
class MissingPreferredElementTypeRule(Rule):
    id = "missing-preferred-element-type"
    severity = "warning"
    skip_in_tests = True
    description = (
        "matmul/conv call on reduced-precision operands without an "
        "explicit accumulation dtype (preferred_element_type)"
    )
    doc_why = (
        "the MXU accumulates in f32 but truncates back per tile; the "
        "repo idiom is preferred_element_type=jnp.float32 (see "
        "ops/flash.py)"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        for region, _dt, why, sd in _reduced_regions(ctx):
            yield from _pet_scan(self, ctx, region.node, sd, why)


# ------------------------------------------------------- project layer


def dtype_project_findings(graph, contexts: dict) -> Iterator:
    """silent-upcast / missing-preferred-element-type through call chains:
    helpers reachable from a REDUCED jit entry are analyzed with params
    seeded to the entry's reduced dtype (the entry passes its traced
    values on), each finding carrying the call path that justifies the
    seeding. Helpers that are themselves lexical regions are the per-file
    pass's job and are skipped, mirroring interproc._host_sync_findings."""
    from .callgraph import MAX_DEPTH, _fmt
    from .core import RULES

    upcast = RULES["silent-upcast"]
    pet = RULES["missing-preferred-element-type"]

    lexical_nodes = {
        id(r.node)
        for regions in graph.regions_by_module.values()
        for r in regions
    }
    entries: list = []
    for mi in graph.index.modules.values():
        ctx = contexts.get(mi.path)
        if ctx is None:
            continue
        pol = _policies(ctx)
        for region in graph.regions_by_module.get(mi.modname, ()):
            red = region_reduced(region, pol)
            if red is None:
                continue
            fi = graph.index.function_for_node(region.node)
            if fi is not None:
                entries.append((fi, red))

    reach: dict = {}  # qualname -> (dtype, why, trace hops)
    frontier = []
    for fi, (dt, why) in entries:
        if fi.qualname not in reach:
            reach[fi.qualname] = (
                dt,
                why,
                [f"reduced jit entry {_fmt(fi)} [{why}]"],
            )
            frontier.append(fi)
    depth = 0
    while frontier and depth < MAX_DEPTH:
        depth += 1
        nxt = []
        for fi in frontier:
            dt, why, trace = reach[fi.qualname]
            for callee, line in graph.edges.get(fi.qualname, ()):
                if callee.qualname in reach:
                    continue
                reach[callee.qualname] = (
                    dt,
                    why,
                    trace + [f"{_fmt(callee)} called at line {line}"],
                )
                nxt.append(callee)
        frontier = nxt

    entry_quals = {fi.qualname for fi, _ in entries}
    for qual, (dt, why, trace) in reach.items():
        if qual in entry_quals:
            continue
        fi = graph.index.functions.get(qual)
        if fi is None or id(fi.node) in lexical_nodes:
            continue
        ctx = contexts.get(fi.path)
        if ctx is None:
            continue
        sd = ScopeDtypes(fi.node, seed={p: dt for p in fi.params})
        why_chain = f"{why}, via caller"

        def trace_fn(node, _fi=fi, _trace=trace):
            return _trace + [f"{_fi.name} ({_fi.path}:{node.lineno})"]

        yield from _upcast_scan(upcast, ctx, fi.node, sd, why_chain, trace_fn)
        yield from _pet_scan(pet, ctx, fi.node, sd, why_chain, trace_fn)
