"""Config static analysis: ``conf/**/*.yaml`` cross-checked against the
schema dataclasses — without importing either.

The reference repo's central defect was an unregistered Hydra schema that
validated nothing (SURVEY §2.1). This repo validates at COMPOSE time
(config/schema.py), but compose-time validation only sees the configs a
run actually composes: a typo'd key in a group file nobody smoke-tested,
a ``defaults:`` entry pointing at a deleted option file, or a schema
field no code ever reads all survive until the one run that needed them.
These rules close that gap statically: every yaml under conf/ is checked
against the schema ON EVERY LINT, config composed or not.

Everything is AST/yaml-node based — the schema is parsed, not imported
(importing config.schema would drag in the package and, transitively,
jax; this package's contract is stdlib+pyyaml only). The cost of that
choice: only statically-decidable facts are checked (literal values
against literal choice sets, yaml node types against annotation names),
which is exactly the niche compose-time validation cannot cover anyway.

Rules (each pinned by a catching/non-catching fixture pair in
tests/test_analysis.py):

* ``conf-duplicate-key``     — a mapping key repeated (pyyaml keeps the
  LAST silently; the loser value vanishes with no trace)
* ``conf-unknown-key``       — key absent from the group's dataclass
* ``conf-bad-choice``        — literal value outside the field's
  ``_check_choice`` set (PRUNE_METHODS, OPTIMIZERS, ...)
* ``conf-type-mismatch``     — yaml value that the schema's coercion
  (``config/schema.py:_coerce``) would reject or silently mistype
* ``conf-missing-group-file``— ``defaults:`` entry naming a group option
  with no ``conf/<group>/<option>.yaml`` behind it
* ``conf-dead-schema-field`` — a schema field no code outside
  config/schema.py ever reads via attribute access (validated-but-unused
  config surface; waive at the field with the dynamic access path if one
  exists)

Waivers work in YAML too: ``# graftlint: disable=<rule> -- reason`` on
the offending line (or alone on the line above it), same syntax and the
same stale-waiver accounting as Python comments.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Optional

import yaml

from .core import Finding, Waiver, _WAIVER_RE

__all__ = ["CONF_RULES", "SchemaModel", "analyze_conf", "parse_yaml_waivers"]


@dataclasses.dataclass(frozen=True)
class ConfRule:
    id: str
    severity: str
    description: str
    # Why the hazard matters — the README catalog column `--rule-docs`
    # generates, same contract as Rule.doc_why.
    doc_why: str = ""


CONF_RULES = {
    r.id: r
    for r in [
        ConfRule(
            "conf-duplicate-key",
            "error",
            "duplicate mapping key in a config yaml — pyyaml silently "
            "keeps the last one and the earlier value vanishes",
            "the earlier value looks set in the file but never applies — "
            "an invisible override",
        ),
        ConfRule(
            "conf-unknown-key",
            "error",
            "config key not present in the group's schema dataclass — "
            "the knob silently does nothing",
            "the silent no-op config knob is this repo's original "
            "root-cause bug class (see ISSUE history)",
        ),
        ConfRule(
            "conf-bad-choice",
            "error",
            "literal config value outside the field's declared choice set "
            "(PRUNE_METHODS, OPTIMIZERS, ...)",
            "fails deep in the run (or never, with a fallback) instead "
            "of at compose time",
        ),
        ConfRule(
            "conf-type-mismatch",
            "error",
            "yaml value whose type the schema field cannot coerce "
            "(per config/schema.py:_coerce semantics)",
            "coercion surprises surface as shape/dtype errors far from "
            "the yaml line that caused them",
        ),
        ConfRule(
            "conf-missing-group-file",
            "error",
            "defaults: entry pointing at a conf/<group>/<option>.yaml "
            "that does not exist",
            "composition fails at runtime on a path typo that was "
            "knowable statically",
        ),
        ConfRule(
            "conf-dead-schema-field",
            "warning",
            "schema dataclass field never read via attribute access by "
            "any code outside config/schema.py — dead config surface",
            "a knob wired to nothing misleads every future reader into "
            "tuning it",
        ),
    ]
}


# ------------------------------------------------------------ yaml waivers


def parse_yaml_waivers(source: str, file: str) -> list:
    """``# graftlint: disable=...`` comments in a yaml file. Line-based
    (yaml comments can't be tokenized like Python's, and ``#`` inside
    quoted scalars is rare enough in config files to accept the risk):
    an inline comment waives its own line, a comment-only line waives the
    next non-blank, non-comment line."""
    lines = source.splitlines()
    waivers = []
    for i, line in enumerate(lines, start=1):
        hash_pos = line.find("#")
        if hash_pos < 0:
            continue
        m = _WAIVER_RE.search(line[hash_pos:])
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(","))
        standalone = line.strip().startswith("#")
        applies_to = i
        if standalone:
            for j in range(i + 1, len(lines) + 1):
                nxt = lines[j - 1].strip()
                if nxt and not nxt.startswith("#"):
                    applies_to = j
                    break
        waivers.append(
            Waiver(
                file=file,
                line=i,
                rules=rules,
                reason=m.group(2),
                applies_to=applies_to,
            )
        )
    return waivers


# ----------------------------------------------------------- schema model


@dataclasses.dataclass
class FieldSpec:
    name: str
    annotation: str
    line: int
    choices: Optional[tuple] = None  # literal choice set when validated


@dataclasses.dataclass
class SchemaModel:
    """The schema file, statically parsed: choice sets, dataclasses with
    their field specs, and the MainConfig group -> dataclass mapping."""

    path: str
    choice_sets: dict = dataclasses.field(default_factory=dict)
    dataclasses_: dict = dataclasses.field(default_factory=dict)
    # MainConfig field name -> dataclass name ("dataset_params" -> ...)
    groups: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, tree: ast.Module) -> Optional["SchemaModel"]:
        model = cls(path=str(path))
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                values = _literal_tuple(node.value)
                if isinstance(t, ast.Name) and values is not None:
                    model.choice_sets[t.id] = values
            elif isinstance(node, ast.ClassDef) and _is_dataclass(node):
                model._parse_dataclass(node)
        if "MainConfig" not in model.dataclasses_:
            return None
        for spec in model.dataclasses_["MainConfig"].values():
            inner = _strip_optional(spec.annotation)
            if inner in model.dataclasses_:
                model.groups[spec.name] = inner
        return model

    def _parse_dataclass(self, node: ast.ClassDef) -> None:
        fields: dict = {}
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                fields[stmt.target.id] = FieldSpec(
                    name=stmt.target.id,
                    annotation=_ann_str(stmt.annotation),
                    line=stmt.lineno,
                )
            elif (
                isinstance(stmt, ast.FunctionDef) and stmt.name == "validate"
            ):
                self._parse_choices(stmt, fields)
        self.dataclasses_[node.name] = fields

    def _parse_choices(self, fn: ast.FunctionDef, fields: dict) -> None:
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_check_choice"
                and len(node.args) >= 3
            ):
                continue
            target = node.args[1]
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and target.attr in fields
            ):
                continue
            choices_node = node.args[2]
            if isinstance(choices_node, ast.Name):
                choices = self.choice_sets.get(choices_node.id)
            else:
                choices = _literal_tuple(choices_node)
            if choices:
                fields[target.attr] = dataclasses.replace(
                    fields[target.attr], choices=choices
                )


def _is_dataclass(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        name = dec
        if isinstance(dec, ast.Call):
            name = dec.func
        if isinstance(name, ast.Name) and name.id == "dataclass":
            return True
        if isinstance(name, ast.Attribute) and name.attr == "dataclass":
            return True
    return False


def _ann_str(node: ast.AST) -> str:
    return ast.unparse(node)


def _strip_optional(ann: str) -> str:
    ann = ann.strip().strip("\"'")
    m = re.fullmatch(r"(?:typing\.)?Optional\[(.+)\]", ann)
    return m.group(1).strip() if m else ann


def _literal_tuple(node: ast.AST) -> Optional[tuple]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not isinstance(elt, ast.Constant):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def find_schema(contexts: dict) -> Optional[SchemaModel]:
    """The schema module among the analyzed files: any module whose AST
    defines a dataclass named MainConfig (config/schema.py here, a
    look-alike in fixture suites)."""
    for path, ctx in contexts.items():
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == "MainConfig":
                model = SchemaModel.parse(path, ctx.tree)
                if model is not None:
                    return model
    return None


# -------------------------------------------------------- type compatibility


def _int_like(value) -> bool:
    if isinstance(value, bool):
        return True  # bool subclasses int; _coerce passes it through
    if isinstance(value, int):
        return True
    if isinstance(value, str):
        try:
            int(value)
            return True
        except ValueError:
            return False
    return False


def _float_like(value) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, (int, float)):
        return True
    if isinstance(value, str):
        # YAML 1.1 reads 5e-4 as a str; _coerce float()s it
        try:
            float(value)
            return True
        except ValueError:
            return False
    return False


def _bool_like(value) -> bool:
    return isinstance(value, bool) or (
        isinstance(value, str) and value.lower() in ("true", "false")
    )


def _type_problem(spec: FieldSpec, value, model: SchemaModel) -> Optional[str]:
    """Why ``value`` cannot inhabit the field, or None when it can
    (mirrors config/schema.py:_coerce leniency exactly — a finding here
    means compose WOULD fail or silently mistype)."""
    ann = spec.annotation.strip().strip("\"'")
    optional = ann != (base := _strip_optional(ann))
    if value is None:
        if optional:
            return None
        return f"null is not a valid {ann}"
    if base in model.dataclasses_:
        if not isinstance(value, dict):
            return f"expected a mapping ({base}), got {type(value).__name__}"
        return None
    if base == "int":
        if not _int_like(value):
            return f"{value!r} is not coercible to int"
    elif base == "float":
        if not _float_like(value):
            return f"{value!r} is not coercible to float"
    elif base == "bool":
        if not _bool_like(value):
            return f"{value!r} is not a bool"
    elif base == "str":
        if not isinstance(value, str):
            return (
                f"{value!r} ({type(value).__name__}) where the schema "
                "declares str — quote it if it is meant literally"
            )
    elif base == "list" or base.startswith("list["):
        if not isinstance(value, list):
            return f"expected a sequence, got {type(value).__name__}"
    return None


# ------------------------------------------------------------- yaml walking


def _conf_finding(file, line, rule_id: str, message: str) -> Finding:
    rule = CONF_RULES[rule_id]
    return Finding(
        file=str(file),
        line=line,
        col=0,
        rule=rule_id,
        severity=rule.severity,
        message=message,
    )


class _NodeLoader(yaml.SafeLoader):
    """SafeLoader used only to compose nodes / construct sub-values."""


def _compose(source: str):
    loader = _NodeLoader(source)
    try:
        return loader, loader.get_single_node()
    finally:
        loader.dispose()


def _mapping_items(node):
    """(key_str, key_line, value_node) for a yaml MappingNode."""
    if not isinstance(node, yaml.MappingNode):
        return []
    out = []
    for key_node, value_node in node.value:
        if isinstance(key_node, yaml.ScalarNode):
            out.append(
                (key_node.value, key_node.start_mark.line + 1, value_node)
            )
    return out


def _construct(loader, node):
    try:
        return loader.construct_object(node, deep=True)
    except yaml.YAMLError:
        return None


def _duplicate_key_findings(file, node) -> list:
    findings = []
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, yaml.MappingNode):
            seen: dict = {}
            for key_node, value_node in n.value:
                stack.append(value_node)
                if not isinstance(key_node, yaml.ScalarNode):
                    continue
                k = key_node.value
                line = key_node.start_mark.line + 1
                if k in seen:
                    findings.append(
                        _conf_finding(
                            file,
                            line,
                            "conf-duplicate-key",
                            f"key {k!r} already defined at line {seen[k]} — "
                            "pyyaml keeps only this occurrence and the "
                            "earlier value silently vanishes",
                        )
                    )
                else:
                    seen[k] = line
        elif isinstance(n, yaml.SequenceNode):
            stack.extend(n.value)
    return findings


def _check_group_mapping(
    file, loader, node, cls_name: str, model: SchemaModel, where: str
) -> list:
    """Keys/values of one mapping against one dataclass's fields."""
    findings = []
    fields = model.dataclasses_.get(cls_name, {})
    for key, line, value_node in _mapping_items(node):
        if key == "defaults":
            continue  # composition machinery, checked separately
        if key not in fields:
            known = ", ".join(sorted(fields)) or "<none>"
            findings.append(
                _conf_finding(
                    file,
                    line,
                    "conf-unknown-key",
                    f"{where}: {key!r} is not a field of {cls_name} — the "
                    f"knob silently does nothing (known: {known})",
                )
            )
            continue
        spec = fields[key]
        value = _construct(loader, value_node)
        vline = value_node.start_mark.line + 1
        if spec.choices is not None and isinstance(value, str):
            if value not in spec.choices:
                findings.append(
                    _conf_finding(
                        file,
                        vline,
                        "conf-bad-choice",
                        f"{where}.{key} = {value!r} not in "
                        f"{tuple(spec.choices)}",
                    )
                )
                continue
        problem = _type_problem(spec, value, model)
        if problem is not None:
            findings.append(
                _conf_finding(
                    file,
                    vline,
                    "conf-type-mismatch",
                    f"{where}.{key} (declared {spec.annotation}): {problem}",
                )
            )
        elif isinstance(value, dict):
            inner = _strip_optional(spec.annotation)
            if inner in model.dataclasses_:
                findings.extend(
                    _check_group_mapping(
                        file,
                        loader,
                        value_node,
                        inner,
                        model,
                        f"{where}.{key}",
                    )
                )
    return findings


def _check_defaults(file, loader, node, conf_root, model) -> list:
    """The ``defaults:`` list of a top-level config."""
    findings = []
    for key, line, value_node in _mapping_items(node):
        if key != "defaults":
            continue
        if not isinstance(value_node, yaml.SequenceNode):
            findings.append(
                _conf_finding(
                    file,
                    line,
                    "conf-type-mismatch",
                    "defaults must be a list of 'group: option' entries",
                )
            )
            continue
        for entry in value_node.value:
            eline = entry.start_mark.line + 1
            if isinstance(entry, yaml.ScalarNode):
                if entry.value != "_self_":
                    findings.append(
                        _conf_finding(
                            file,
                            eline,
                            "conf-type-mismatch",
                            f"defaults entry {entry.value!r} must be "
                            "'_self_' or 'group: option'",
                        )
                    )
                continue
            items = _mapping_items(entry)
            if len(items) != 1:
                findings.append(
                    _conf_finding(
                        file,
                        eline,
                        "conf-type-mismatch",
                        "defaults entry must be a single 'group: option'",
                    )
                )
                continue
            group, gline, option_node = items[0]
            if model is not None and group not in model.groups:
                findings.append(
                    _conf_finding(
                        file,
                        gline,
                        "conf-unknown-key",
                        f"defaults group {group!r} is not a MainConfig "
                        f"field (known groups: "
                        f"{', '.join(sorted(model.groups))})",
                    )
                )
                continue
            option = _construct(loader, option_node)
            if option is None:
                continue  # 'group: null' disables the group
            target = Path(conf_root) / group / f"{option}.yaml"
            if not target.exists():
                findings.append(
                    _conf_finding(
                        file,
                        gline,
                        "conf-missing-group-file",
                        f"defaults entry '{group}: {option}' points at "
                        f"missing {target}",
                    )
                )
    return findings


def _dead_field_findings(model: SchemaModel, contexts: dict) -> list:
    """Schema fields never read via attribute access outside the schema
    module itself. validate()-only reads deliberately do NOT count as
    uses — a field that is checked but never consumed is exactly the
    validated-but-dead surface this rule exists to expose."""
    read_attrs: set = set()
    for path, ctx in contexts.items():
        if path == model.path:
            continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                read_attrs.add(node.attr)
    findings = []
    for cls_name, fields in model.dataclasses_.items():
        for spec in fields.values():
            if spec.name in read_attrs:
                continue
            findings.append(
                _conf_finding(
                    model.path,
                    spec.line,
                    "conf-dead-schema-field",
                    f"{cls_name}.{spec.name} is never read via attribute "
                    "access outside the schema module — dead config "
                    "surface (drop it, or waive with the dynamic access "
                    "path that consumes it)",
                )
            )
    return findings


# ---------------------------------------------------------------- driver


def analyze_conf(yaml_files, contexts: dict) -> tuple:
    """``(findings, waivers)`` for ``[(yaml_path, conf_root), ...]``.

    ``contexts`` (path -> parsed module) supplies the schema — any module
    defining a MainConfig dataclass — and the package trees for the
    dead-field scan. Without a schema only the schema-independent rules
    run (duplicate keys, defaults-entry shape)."""
    model = find_schema(contexts)
    findings: list = []
    waivers: list = []
    for path, conf_root in yaml_files:
        source = Path(path).read_text(encoding="utf-8")
        waivers.extend(parse_yaml_waivers(source, str(path)))
        try:
            loader, node = _compose(source)
        except yaml.YAMLError as e:
            mark = getattr(e, "problem_mark", None)
            findings.append(
                Finding(
                    file=str(path),
                    line=(mark.line + 1) if mark else 1,
                    col=0,
                    rule="parse-error",
                    severity="error",
                    message=f"yaml does not parse: {e}",
                )
            )
            continue
        if node is None:
            continue  # empty file
        findings.extend(_duplicate_key_findings(path, node))
        if not isinstance(node, yaml.MappingNode):
            findings.append(
                _conf_finding(
                    path, 1, "conf-type-mismatch",
                    "config file must contain a mapping",
                )
            )
            continue
        rel = _relparts(path, conf_root)
        if model is None:
            findings.extend(
                _check_defaults(path, loader, node, conf_root, None)
            )
            continue
        if len(rel) >= 2:
            group = rel[0]
            if group not in model.groups:
                findings.append(
                    _conf_finding(
                        path,
                        1,
                        "conf-unknown-key",
                        f"config group directory {group!r} does not match "
                        "any MainConfig field (known groups: "
                        f"{', '.join(sorted(model.groups))})",
                    )
                )
            else:
                findings.extend(
                    _check_group_mapping(
                        path, loader, node, model.groups[group], model, group
                    )
                )
        else:
            findings.extend(
                _check_defaults(path, loader, node, conf_root, model)
            )
            findings.extend(
                _check_group_mapping(
                    path, loader, node, "MainConfig", model, rel[-1]
                )
            )
    if model is not None and yaml_files:
        findings.extend(_dead_field_findings(model, contexts))
    return findings, waivers


def _relparts(path, conf_root) -> tuple:
    try:
        return Path(path).relative_to(conf_root).parts
    except ValueError:
        return (Path(path).name,)
