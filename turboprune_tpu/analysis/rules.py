"""The graftlint rule set: nine JAX failure classes, tuned to this repo.

Every rule documents WHY its pattern matters on TPU, because the finding
message is what a contributor sees at review time. Severities: "error" for
patterns that corrupt results or deadlock (host syncs in compiled code,
key reuse, rank-conditional collectives, donated-buffer reads), "warning"
for patterns that burn performance or hide failures (retraces, swallowed
exceptions, debug prints). The CLI gates on BOTH — a warning you disagree
with gets an inline waiver with a reason, not silence.

Each rule has a catching + non-catching fixture pair in
tests/test_analysis.py; change a rule and its fixtures together.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .core import ModuleContext, Rule, register
from .regions import (
    donation_spec,
    dotted_name,
    is_jit_wrapper,
    literal_str_seq,
    param_names,
)

# ------------------------------------------------------------------ helpers

# Attribute reads that are STATIC under tracing (shape metadata): names that
# only appear under these are not device values.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _tail(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _root(name: Optional[str]) -> str:
    return name.split(".", 1)[0] if name else ""


def _target_names(t: ast.AST) -> list:
    """Top-level assignable dotted names of an assignment target —
    ``self.state, m`` -> ["self.state", "m"] (NOT the nested "self")."""
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    name = dotted_name(t)
    return [name] if name else []


def _walk_prune_calls(node: ast.AST):
    """Walk an expression WITHOUT descending into nested Call nodes —
    names belong to the innermost call that receives them."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.Call):
                continue
            stack.append(child)


def _names_directly_under(call: ast.Call) -> list:
    """Dotted names appearing as (sub)expressions of a call's arguments,
    excluding anything inside a nested call (a nested call is charged
    separately, when the walk reaches it)."""
    out = []
    for arg in list(call.args) + [k.value for k in call.keywords]:
        if isinstance(arg, ast.Call):
            continue
        for n in _walk_prune_calls(arg):
            name = dotted_name(n)
            if name and isinstance(n, (ast.Name, ast.Attribute)):
                out.append(name)
    return out


def _terminates(stmts) -> bool:
    """True when control cannot fall off the end of this statement list."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue)
    )


def _traced_name_hits(expr: ast.AST, traced: frozenset) -> list:
    """Names of traced params used as VALUES in ``expr`` — occurrences
    under ``.shape``/``.ndim``/``.dtype``/``.size`` are static metadata
    and don't count."""
    shielded: set = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            for inner in ast.walk(node.value):
                if isinstance(inner, ast.Name):
                    shielded.add(id(inner))
    return [
        n
        for n in ast.walk(expr)
        if isinstance(n, ast.Name)
        and n.id in traced
        and id(n) not in shielded
    ]


def _function_scopes(tree: ast.Module):
    """(scope_node, scope_body, param_names) for the module and each def —
    nested defs are yielded separately and excluded from their parent's
    body walk. scope_node is None for module scope (project mode uses it
    to resolve ``self.m()`` and nested-def calls)."""
    yield None, _own_statements(tree.body), []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, _own_statements(node.body), param_names(node)


def _names_in_arg(expr: ast.AST) -> list:
    """Dotted names in one argument expression, excluding nested calls
    (same attribution discipline as :func:`_names_directly_under`)."""
    if isinstance(expr, ast.Call):
        return []
    out = []
    for n in _walk_prune_calls(expr):
        name = dotted_name(n)
        if name and isinstance(n, (ast.Name, ast.Attribute)):
            out.append(name)
    return out


def _own_statements(body):
    """The statement list with nested function/class defs snipped out (they
    form their own scopes)."""
    return [
        stmt
        for stmt in body
        if not isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        )
    ]


def _walk_no_nested_defs(stmts):
    """Walk statements without descending into nested def/class bodies."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            stack.append(child)


# ---------------------------------------------------------- 1 jit-host-sync


@register
class JitHostSyncRule(Rule):
    """Host-device syncs inside compiled code.

    ``.item()``, ``np.asarray``, ``jax.device_get`` etc. inside a
    ``jax.jit``/``shard_map``/``lax.scan`` body either fail at trace time
    or — worse, under ``jax.debug``-style escapes — force a device->host
    round trip that serializes the XLA pipeline. On TPU that's the
    difference between a scan-epoch running as one program and a hot loop
    bottlenecked on PCIe-sized latencies.
    """

    id = "jit-host-sync"
    severity = "error"
    description = (
        "host-sync op (.item()/float()/np.array/jax.device_get) reachable "
        "inside jit/shard_map/lax.scan-traced code"
    )
    doc_why = (
        "a device->host sync in compiled code serializes the XLA pipeline "
        '-- the scan-epoch "one program per epoch" property dies'
    )

    _SYNC_METHODS = {"item", "tolist", "block_until_ready"}
    _NUMPY_ROOTS = {"np", "numpy", "onp"}
    _NUMPY_PULLS = {"array", "asarray", "asanyarray", "frombuffer", "copy"}

    def check(self, ctx: ModuleContext) -> Iterator:
        for region in ctx.jit_regions:
            for node in region.walk():
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = dotted_name(f)
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in self._SYNC_METHODS
                ):
                    yield ctx.finding(
                        self,
                        node,
                        f".{f.attr}() inside code traced via "
                        f"{region.reason} — device->host sync; return the "
                        "array and read it outside the compiled region",
                    )
                elif _tail(name) == "device_get" and _root(name) in (
                    "jax",
                    "device_get",
                ):
                    yield ctx.finding(
                        self,
                        node,
                        f"jax.device_get inside code traced via "
                        f"{region.reason} — host transfer in a compiled "
                        "body; hoist it to the caller",
                    )
                elif (
                    _root(name) in self._NUMPY_ROOTS
                    and _tail(name) in self._NUMPY_PULLS
                ):
                    yield ctx.finding(
                        self,
                        node,
                        f"{name}(...) inside code traced via "
                        f"{region.reason} — numpy materializes on host; "
                        "use jnp",
                    )
                elif (
                    isinstance(f, ast.Name)
                    and f.id in ("float", "int", "bool")
                    and node.args
                ):
                    hits = _traced_name_hits(
                        node.args[0], region.traced_params
                    )
                    if hits:
                        yield ctx.finding(
                            self,
                            node,
                            f"{f.id}({hits[0].id}) on a traced value "
                            f"inside code traced via {region.reason} — "
                            "concretization error / host sync; keep it a "
                            "jnp array (shape/dtype reads are fine)",
                        )


# --------------------------------------------------------- 2 retrace-hazard


@register
class RetraceHazardRule(Rule):
    """jit construction in places that defeat the trace cache.

    ``jax.jit`` caches on the FUNCTION OBJECT: jit inside a loop, jit of a
    fresh lambda, or build-and-immediately-call (``jax.jit(f)(x)``) inside
    a function hands the cache a new key per call — a silent recompile
    every iteration, which on TPU means seconds of XLA compile time paid
    per step. Tests are exempt (skip_in_tests): one-shot jits in a test
    body compile exactly once by construction.
    """

    id = "retrace-hazard"
    severity = "warning"
    skip_in_tests = True
    description = (
        "jax.jit constructed in a loop / of a fresh lambda / "
        "built-and-called inline — defeats the trace cache, recompiles "
        "per call"
    )
    doc_why = (
        "jit caches on the function object; each of these recompiles per "
        "call (seconds of XLA compile per step)"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        reported: set = set()

        def report(node, msg):
            if node.lineno not in reported:
                reported.add(node.lineno)
                yield ctx.finding(self, node, msg)

        # stack-walk with loop/function depth
        def visit(node, loops: int, funcs: int):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                loops += 1
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                funcs += 1
            if isinstance(node, ast.Call):
                if is_jit_wrapper(node.func):
                    if loops:
                        yield from report(
                            node,
                            f"{dotted_name(node.func)} constructed inside "
                            "a loop — compiles every iteration; hoist it "
                            "out (jit caches on the function object)",
                        )
                    elif funcs and node.args and isinstance(
                        node.args[0], ast.Lambda
                    ):
                        yield from report(
                            node,
                            "jit of a lambda inside a function — a fresh "
                            "function object per call means a fresh trace "
                            "per call; def it at module scope",
                        )
                # jax.jit(f)(x) / jax.jit(f).lower(...) inside a function
                if (
                    funcs
                    and isinstance(node.func, ast.Call)
                    and is_jit_wrapper(node.func.func)
                ):
                    yield from report(
                        node,
                        "jit built and invoked in one expression inside a "
                        "function — the compiled fn is discarded and "
                        "re-traced on the next call; cache it",
                    )
                if (
                    funcs
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("lower", "trace")
                    and isinstance(node.func.value, ast.Call)
                    and is_jit_wrapper(node.func.value.func)
                ):
                    yield from report(
                        node,
                        f"jit(...).{node.func.attr}() inside a function — "
                        "re-traces every call unless the result is cached",
                    )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, loops, funcs)

        yield from visit(ctx.tree, 0, 0)


# ------------------------------------------------- 3 static-argnames-mismatch


@register
class StaticArgnamesMismatchRule(Rule):
    """``static_argnames`` naming a parameter that doesn't exist.

    jax only validates static_argnames lazily (and historically only
    warned), so a typo'd or stale name silently makes the argument TRACED
    — every distinct Python value then recompiles instead of specializing,
    and `if flag:` on it becomes a tracer error far from the typo.
    """

    id = "static-argnames-mismatch"
    severity = "error"
    description = (
        "static_argnames/static_argnums referencing parameters absent "
        "from the jitted function's signature"
    )
    doc_why = (
        "the typo'd argument silently stays traced -> recompile per "
        "Python value, tracer errors far from the cause"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        defs = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

        def jit_call_sites():
            # decorator form: @partial(jax.jit, static_argnames=...) /
            # @jax.jit(static_argnames=...)
            for fn in defs.values():
                for dec in fn.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    if is_jit_wrapper(dec.func):
                        yield dec, fn
                    elif (
                        dotted_name(dec.func)
                        in ("partial", "functools.partial")
                        and dec.args
                        and is_jit_wrapper(dec.args[0])
                    ):
                        yield dec, fn
            # call form: jax.jit(f, static_argnames=...) with local f
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Call)
                    and is_jit_wrapper(node.func)
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in defs
                ):
                    yield node, defs[node.args[0].id]

        seen: set = set()
        for call, fn in jit_call_sites():
            key = (call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            sig = set(param_names(fn))
            has_kwargs = fn.args.kwarg is not None
            for kw in call.keywords:
                if kw.arg == "static_argnames" and not has_kwargs:
                    for name in literal_str_seq(kw.value) or []:
                        if name not in sig:
                            yield ctx.finding(
                                self,
                                call,
                                f"static_argnames={name!r} is not a "
                                f"parameter of {fn.name}() — the intended "
                                "argument stays traced and recompiles per "
                                "value",
                            )
                elif kw.arg == "static_argnums" and not fn.args.vararg:
                    npos = len(fn.args.posonlyargs) + len(fn.args.args)
                    nums = kw.value
                    elts = (
                        nums.elts
                        if isinstance(nums, (ast.Tuple, ast.List))
                        else [nums]
                    )
                    for elt in elts:
                        if (
                            isinstance(elt, ast.Constant)
                            and isinstance(elt.value, int)
                            and elt.value >= npos
                        ):
                            yield ctx.finding(
                                self,
                                call,
                                f"static_argnums={elt.value} is out of "
                                f"range for {fn.name}() ({npos} positional "
                                "parameters)",
                            )


# ----------------------------------------------------------- 4 rng-key-reuse


# jax.random callables that DERIVE rather than consume entropy; everything
# else in jax.random consumes the key it's given.
_KEY_DERIVERS = {"fold_in", "clone", "key_data", "wrap_key_data"}
_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "clone"}


def _is_jax_random(name: Optional[str]) -> bool:
    if not name:
        return False
    parts = name.split(".")
    return len(parts) >= 2 and parts[-2] == "random"


@register
class RngKeyReuseRule(Rule):
    """A PRNG key consumed twice, or a constant key baked into library code.

    JAX keys are single-use by contract: two draws from one key are
    CORRELATED, not independent — e.g. cutout squares landing on the crop
    offsets, or every serving replica "randomly" picking the same thing.
    The repo's discipline (data/cifar.py) is fold_in(base, counter) then
    split — fold_in/clone derive and are exempt; split and every sampler
    consume. Constant ``PRNGKey(0)`` in library code pins every caller to
    one stream (tests are exempt: determinism there is the point).
    """

    id = "rng-key-reuse"
    severity = "error"
    description = (
        "PRNG key consumed twice without split, or constant PRNGKey in "
        "library code"
    )
    doc_why = (
        "reused keys give CORRELATED draws (augmentation, init, pruning "
        "all quietly share randomness); constant keys pin every caller "
        "to one stream"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        # --- part A: constant keys (library code only)
        if not ctx.is_test:
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Call)
                    and _is_jax_random(dotted_name(node.func))
                    and _tail(dotted_name(node.func)) in ("PRNGKey", "key")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, int)
                ):
                    yield ctx.finding(
                        self,
                        node,
                        f"constant {_tail(dotted_name(node.func))}"
                        f"({node.args[0].value}) in library code — every "
                        "caller shares one stream; thread a seed/key in",
                    )

        # --- part B: per-scope double consumption
        for scope, body, params in _function_scopes(ctx.tree):
            yield from self._check_scope(ctx, body, params, scope)

    def check_project(self, ctx: ModuleContext, view) -> Iterator:
        """Part B again, with the project view resolving helper calls to
        their key-consumption summaries — ``draw(key); draw(key)`` fires
        even when ``draw`` lives in another module."""
        for scope, body, params in _function_scopes(ctx.tree):
            yield from self._check_scope(ctx, body, params, scope, view)

    _KEYISH_PARAM = ("key", "rng", "prng")

    def _check_scope(self, ctx, body, params, scope=None, view=None) -> Iterator:
        findings: dict = {}  # (line, name) -> Finding
        uses: dict = {}  # key name -> first-use line (0 = unconsumed)

        # Parameters that are keys by naming convention are tracked too —
        # `def f(key): a = normal(key); b = normal(key)` is the classic
        # bug. Only when the scope actually hands them to jax.random,
        # though: a numpy Generator named `rng` (data/imagenet.py crop
        # sampling) is stateful and reuses legitimately.
        keyish = [
            p
            for p in params
            if any(tok in p.lower() for tok in self._KEYISH_PARAM)
        ]
        if keyish:
            fed_to_jax_random: set = set()
            for stmt in body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    if _is_jax_random(dotted_name(sub.func)):
                        fed_to_jax_random.update(_names_directly_under(sub))
                    elif view is not None:
                        # project mode: a keyish param handed to a resolved
                        # key-CONSUMING helper is tracked too.
                        info = view.rng_call_info(sub, scope)
                        if info is not None:
                            for arg, _witness in info:
                                fed_to_jax_random.update(_names_in_arg(arg))
            for p in keyish:
                if p in fed_to_jax_random:
                    uses[p] = 0

        def assign_target(t):
            for name in _target_names(t):
                uses.pop(name, None)

        def is_key_producer(value) -> bool:
            return (
                isinstance(value, ast.Call)
                and _is_jax_random(dotted_name(value.func))
                and _tail(dotted_name(value.func)) in _KEY_MAKERS
            )

        def track_target(t):
            for name in _target_names(t):
                uses[name] = 0  # tracked, unconsumed

        def consume(name, node, via=None):
            if name not in uses:
                return
            if uses[name]:
                key = (node.lineno, name)
                if key not in findings:
                    detail = f" (consumed via {via})" if via else ""
                    findings[key] = ctx.finding(
                        self,
                        node,
                        f"PRNG key {name!r} consumed again (first use "
                        f"line {uses[name]}) without an intervening "
                        f"split/fold_in — draws will be correlated{detail}",
                        trace=[via] if via else None,
                    )
            else:
                uses[name] = node.lineno

        def visit_expr(node):
            # Names are attributed to the INNERMOST call receiving them, so
            # normal(fold_in(key, i)) charges `key` to the exempt fold_in,
            # not to normal.
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fname = dotted_name(sub.func)
                if _is_jax_random(fname) and _tail(fname) in _KEY_DERIVERS:
                    continue
                if view is not None and not _is_jax_random(fname):
                    info = view.rng_call_info(sub, scope)
                    if info is not None:
                        # resolved project callee: charge exactly the args
                        # bound to key-consuming params, nothing else
                        for arg, witness in info:
                            for name in set(_names_in_arg(arg)):
                                consume(name, sub, via=witness)
                        continue
                for name in set(_names_directly_under(sub)):
                    consume(name, sub)

        def visit_stmts(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    value = stmt.value
                    if value is not None:
                        visit_expr(value)
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        if value is not None and is_key_producer(value):
                            track_target(t)
                        else:
                            assign_target(t)
                elif isinstance(stmt, ast.If):
                    visit_expr(stmt.test)
                    snapshot = dict(uses)
                    visit_stmts(_own_statements(stmt.body))
                    after_body = dict(uses)
                    uses.clear()
                    uses.update(snapshot)
                    visit_stmts(_own_statements(stmt.orelse))
                    # A branch ending in return/raise doesn't leak its
                    # consumptions into the fall-through path (the idiom
                    # `if m == "snip": return snip(.., rng)` chains).
                    body_live = not _terminates(stmt.body)
                    else_live = not (
                        stmt.orelse and _terminates(stmt.orelse)
                    )
                    if body_live and not else_live:
                        uses.clear()
                        uses.update(after_body)
                    elif body_live:
                        for name, line in after_body.items():
                            uses[name] = max(uses.get(name, 0), line)
                elif isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                    # Two symbolic iterations: a key defined outside the
                    # loop and consumed inside without per-iteration
                    # rederivation trips on pass two — cross-iteration
                    # reuse. Findings dedupe on (line, name).
                    loop_body = _own_statements(stmt.body)
                    if isinstance(stmt, (ast.For, ast.AsyncFor)):
                        visit_expr(stmt.iter)
                        assign_target(stmt.target)
                    else:
                        visit_expr(stmt.test)
                    visit_stmts(loop_body)
                    visit_stmts(loop_body)
                    visit_stmts(_own_statements(stmt.orelse))
                elif isinstance(stmt, ast.Try):
                    visit_stmts(_own_statements(stmt.body))
                    for h in stmt.handlers:
                        visit_stmts(_own_statements(h.body))
                    visit_stmts(_own_statements(stmt.orelse))
                    visit_stmts(_own_statements(stmt.finalbody))
                elif isinstance(
                    stmt, (ast.With, ast.AsyncWith)
                ):
                    for item in stmt.items:
                        visit_expr(item.context_expr)
                    visit_stmts(_own_statements(stmt.body))
                elif isinstance(
                    stmt,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue  # separate scope
                else:
                    visit_expr(stmt)

        visit_stmts(body)
        yield from findings.values()


# --------------------------------------------------------- 5 collective-order

# jax collectives + multihost utils + this repo's collective-bearing
# wrappers (parallel/multihost.py). Module-level: the callgraph's
# issues-a-collective summary keys off the same set.
_COLLECTIVE_TAILS = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pshuffle",
    "psum_scatter",
    "broadcast_one_to_all",
    "process_allgather",
    "sync_global_devices",
    "assert_equal",
    "broadcast_object",
    "sync_hosts",
    "check_state_equality",
}

# Rank-dependent truth sources: a branch on these is taken by SOME hosts.
_RANK_SOURCES = {"process_index", "is_primary"}


def rank_conditional_test(node: ast.If) -> bool:
    """True when an ``if`` branches on process identity (not uniform
    process_count()-style guards)."""
    test_names = {
        _tail(dotted_name(n)) for n in ast.walk(node.test) if dotted_name(n)
    }
    return bool(test_names & _RANK_SOURCES)


@register
class CollectiveOrderRule(Rule):
    """Collectives under rank-conditional control flow.

    Every collective must be issued by EVERY process in the same order —
    a ``psum``/``broadcast_one_to_all`` under ``if process_index() == 0:``
    (or ``is_primary()``) runs on one host only, and the rest of the pod
    blocks in the next collective forever. Multihost deadlocks like this
    have no traceback: the job just hangs until the scheduler kills it.
    Uniform guards (``process_count() == 1``) are fine and not flagged.
    """

    id = "collective-order"
    severity = "error"
    description = (
        "collective op inside a process_index()/is_primary()-conditional "
        "branch — not all hosts reach it; multihost deadlock"
    )
    doc_why = (
        "hosts that skip the branch never post the collective — the pod "
        "deadlocks with no traceback (process_count() guards are uniform "
        "and exempt)"
    )

    _COLLECTIVES = _COLLECTIVE_TAILS

    def check(self, ctx: ModuleContext) -> Iterator:
        seen: set = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if not rank_conditional_test(node):
                continue
            for branch in (node.body, node.orelse):
                for stmt in branch:
                    for sub in ast.walk(stmt):
                        if (
                            isinstance(sub, ast.Call)
                            and _tail(dotted_name(sub.func))
                            in self._COLLECTIVES
                            and (sub.lineno, sub.col_offset) not in seen
                        ):
                            seen.add((sub.lineno, sub.col_offset))
                            yield ctx.finding(
                                self,
                                sub,
                                f"{dotted_name(sub.func)} under a "
                                "process_index()/is_primary() branch — "
                                "hosts that skip the branch never post "
                                "the collective and the pod deadlocks; "
                                "run it unconditionally and mask the "
                                "result instead",
                            )


# -------------------------------------------------------- 6 donated-arg-reuse


@register
class DonatedArgReuseRule(Rule):
    """Reading a buffer after donating it to a jit.

    ``donate_argnums`` lets XLA alias the argument's HBM for the output
    (parallel/mesh.py relies on it so the optimizer update is in-place).
    The cost: the Python-side array is left pointing at freed/aliased
    memory — reads after the call return garbage or raise, depending on
    backend and timing. The safe idiom is exactly what the harness does:
    rebind the result over the donated name (``state = step(state, ...)``).
    """

    id = "donated-arg-reuse"
    severity = "error"
    description = (
        "argument read after being passed to a donate_argnums jit — the "
        "buffer was donated and may alias the output"
    )
    doc_why = (
        "the buffer was aliased into the output; reads return garbage or "
        "raise depending on backend"
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        for scope, body, _params in _function_scopes(ctx.tree):
            yield from self._check_scope(ctx, body, scope)

    def check_project(self, ctx: ModuleContext, view) -> Iterator:
        """Scope dataflow again, with the project view recognising
        donating FACTORIES from other modules: ``step = make_step(...)``
        where make_step returns ``jax.jit(fn, donate_argnums=(0,))``
        registers ``step`` as a donator here."""
        for scope, body, _params in _function_scopes(ctx.tree):
            yield from self._check_scope(ctx, body, scope, view)

    @staticmethod
    def _donation_spec(call: ast.Call):
        """(argnums, argnames, witness=None) from a jit-wrapper call."""
        spec = donation_spec(call)
        return spec + (None,) if spec is not None else None

    def _check_scope(self, ctx, body, scope=None, view=None) -> Iterator:
        donators: dict = {}  # callable name -> (argnums, argnames, witness)
        dead: dict = {}  # donated var name -> (donation line, witness)
        findings: dict = {}

        def donate_from_call(call: ast.Call, spec) -> None:
            nums, names, witness = spec
            for i in nums:
                if i < len(call.args):
                    name = dotted_name(call.args[i])
                    if name:
                        dead[name] = (call.lineno, witness)
            for kw in call.keywords:
                if kw.arg in names:
                    name = dotted_name(kw.value)
                    if name:
                        dead[name] = (call.lineno, witness)

        def flag_dead_reads(expr) -> None:
            for n in ast.walk(expr):
                name = dotted_name(n)
                if (
                    name in dead
                    and isinstance(n, (ast.Name, ast.Attribute))
                    and isinstance(getattr(n, "ctx", None), ast.Load)
                ):
                    key = (n.lineno, name)
                    if key not in findings:
                        line, witness = dead[name]
                        detail = f" (donating: {witness})" if witness else ""
                        findings[key] = ctx.finding(
                            self,
                            n,
                            f"{name!r} read after being donated at line "
                            f"{line} — the buffer was handed to XLA "
                            "and may be deleted/aliased; rebind the jit's "
                            f"result instead{detail}",
                            trace=[witness] if witness else None,
                        )

        def revive_target(t) -> None:
            for name in _target_names(t):
                dead.pop(name, None)

        def visit_expr(expr) -> None:
            # Reads of buffers killed by PRIOR statements flag first; only
            # then do this statement's own donations take effect (the arg
            # handed to the donating call is a legal last read).
            flag_dead_reads(expr)
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call):
                    continue
                fname = dotted_name(sub.func)
                spec = None
                if fname is not None and fname in donators:
                    spec = donators[fname]
                elif isinstance(sub.func, ast.Call):
                    spec = self._donation_spec(sub.func)  # jit(f, ...)(x)
                if spec is not None:
                    donate_from_call(sub, spec)

        def visit_stmts(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    value = stmt.value
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    if value is not None:
                        spec = (
                            self._donation_spec(value)
                            if isinstance(value, ast.Call)
                            else None
                        )
                        if (
                            spec is None
                            and view is not None
                            and isinstance(value, ast.Call)
                        ):
                            # step = make_step(...) with a cross-module
                            # donating factory (witness names the jit site)
                            spec = view.donating_spec(value, scope)
                        if spec is not None:
                            # g = jax.jit(f, donate_argnums=...)
                            for t in targets:
                                name = dotted_name(t)
                                if name:
                                    donators[name] = spec
                            continue
                        visit_expr(value)
                    for t in targets:
                        revive_target(t)
                elif isinstance(stmt, ast.If):
                    visit_expr(stmt.test)
                    snapshot = dict(dead)
                    visit_stmts(_own_statements(stmt.body))
                    after = dict(dead)
                    dead.clear()
                    dead.update(snapshot)
                    visit_stmts(_own_statements(stmt.orelse))
                    body_live = not _terminates(stmt.body)
                    else_live = not (
                        stmt.orelse and _terminates(stmt.orelse)
                    )
                    if body_live and not else_live:
                        dead.clear()
                        dead.update(after)
                    elif body_live:
                        dead.update(after)  # dead in either branch: dead
                elif isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                    loop_body = _own_statements(stmt.body)
                    if isinstance(stmt, (ast.For, ast.AsyncFor)):
                        visit_expr(stmt.iter)
                        revive_target(stmt.target)
                    else:
                        visit_expr(stmt.test)
                    visit_stmts(loop_body)
                    visit_stmts(loop_body)  # cross-iteration reuse
                    visit_stmts(_own_statements(stmt.orelse))
                elif isinstance(stmt, ast.Try):
                    visit_stmts(_own_statements(stmt.body))
                    for h in stmt.handlers:
                        visit_stmts(_own_statements(h.body))
                    visit_stmts(_own_statements(stmt.orelse))
                    visit_stmts(_own_statements(stmt.finalbody))
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        visit_expr(item.context_expr)
                    visit_stmts(_own_statements(stmt.body))
                elif isinstance(
                    stmt,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue
                else:
                    visit_expr(stmt)

        visit_stmts(body)
        yield from findings.values()


# ------------------------------------------------------------ 7 broad-except


@register
class BroadExceptRule(Rule):
    """``except:``/``except Exception:`` that swallows silently.

    PR 1's root-cause was a config knob that silently did nothing; broad
    handlers are how such bugs hide — an OOM, a shape error, a corrupt
    checkpoint all collapse into "the fallback path ran". A broad catch is
    acceptable only when it RECORDS what it ate (log/print/traceback) or
    re-raises; genuine degrade-don't-die paths that report through other
    channels (e.g. serve/batcher.py futures) carry an inline waiver whose
    reason documents the channel.
    """

    id = "broad-except"
    severity = "warning"
    description = (
        "bare/Exception-wide except that neither logs, re-raises, nor "
        "records the suppressed error"
    )
    doc_why = (
        'silent degradation is how "the config knob did nothing" bugs '
        "survive review"
    )

    _BROAD = {"Exception", "BaseException"}
    _EVIDENCE_CALLS = {
        "print",
        "warn",
        "warning",
        "error",
        "exception",
        "critical",
        "info",
        "debug",
        "log",
        "format_exc",
        "print_exc",
        "fail",
    }
    _EVIDENCE_ROOTS = {"logging", "logger", "warnings", "traceback", "log"}

    def _is_broad(self, handler: ast.ExceptHandler) -> Optional[str]:
        t = handler.type
        if t is None:
            return "bare except"
        names = (
            [dotted_name(e) for e in t.elts]
            if isinstance(t, ast.Tuple)
            else [dotted_name(t)]
        )
        for name in names:
            if name and _tail(name) in self._BROAD:
                return f"except {_tail(name)}"
        return None

    def _has_evidence(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if (
                    _tail(name) in self._EVIDENCE_CALLS
                    or _root(name) in self._EVIDENCE_ROOTS
                ):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = self._is_broad(node)
            if broad and not self._has_evidence(node):
                yield ctx.finding(
                    self,
                    node,
                    f"{broad} swallows the error without logging or "
                    "re-raising — narrow the type, or record what was "
                    "suppressed so real failures stay visible",
                )


# ------------------------------------------------------- 8 debug-in-hot-path


@register
class DebugInHotPathRule(Rule):
    """Debug output inside compiled code.

    A ``print`` inside a jitted body fires at TRACE time only (misleading:
    it prints tracers, once) and ``jax.debug.print``/``callback`` inserts
    a host callback into the compiled program — fine while debugging,
    but in a scan-epoch hot path it stalls the device every step. Neither
    belongs in committed library code.
    """

    id = "debug-in-hot-path"
    severity = "warning"
    description = (
        "print/jax.debug.print/breakpoint inside jit-traced code — "
        "trace-time noise or a per-step host callback in the hot path"
    )
    doc_why = (
        "trace-time-only prints mislead; debug callbacks stall the "
        "device every step"
    )

    _DEBUG_TAILS = {"set_trace", "breakpoint"}

    def check(self, ctx: ModuleContext) -> Iterator:
        for region in ctx.jit_regions:
            for node in region.walk():
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                msg = None
                if name in ("print", "breakpoint"):
                    msg = (
                        f"{name}() inside code traced via {region.reason}"
                        " — executes at trace time only (prints tracers "
                        "once, then never again)"
                    )
                elif ".debug." in f".{name}." and _tail(name) in (
                    "print",
                    "breakpoint",
                    "callback",
                ):
                    msg = (
                        f"{name} inside code traced via {region.reason} — "
                        "host callback compiled into the hot path; remove "
                        "before committing"
                    )
                elif _tail(name) in self._DEBUG_TAILS and _root(name) in (
                    "pdb",
                    "ipdb",
                ):
                    msg = f"{name} inside jit-traced code"
                if msg:
                    yield ctx.finding(self, node, msg)


# ------------------------------------------ 9 unhashable-width-overrides


def _is_dict_expr(node: ast.AST) -> bool:
    return isinstance(node, (ast.Dict, ast.DictComp)) or (
        isinstance(node, ast.Call) and dotted_name(node.func) == "dict"
    )


@register
class UnhashableWidthOverridesRule(Rule):
    """A dict passed as ``width_overrides=`` to anything but create_model.

    Flax modules are frozen dataclasses and their HASH is the jit trace
    cache key: a model built with ``width_overrides={...}`` constructs
    fine, then raises ``TypeError: unhashable type: 'dict'`` at the first
    jitted apply — far from the construction site, typically inside a
    harness step function. The repo's convention is
    ``tuple(sorted(d.items()))`` at the model boundary;
    ``models.create_model`` performs that normalization itself and is the
    one callee a raw dict may flow into. Tests are exempt: the fixture
    models there pin the normalized form explicitly.
    """

    id = "unhashable-width-overrides"
    severity = "warning"
    skip_in_tests = True
    description = (
        "width_overrides passed as a dict to a model factory — flax "
        "Modules hash into the jit cache, so the dict detonates at first "
        "traced apply; normalize with tuple(sorted(d.items())) or go "
        "through create_model"
    )
    doc_why = (
        "flax Modules hash into the jit trace cache; a dict-valued field "
        "raises TypeError at the first traced apply, far from the "
        "construction site"
    )

    # create_model normalizes a raw dict itself; the sparse plan/result
    # containers hold the dict by DESIGN (host-side bookkeeping — their
    # as_override_tuple() is the hashable model boundary).
    _ALLOWED_CALLEES = {"create_model", "CompactionPlan", "CompactionResult"}

    def check(self, ctx: ModuleContext) -> Iterator:
        for _scope, body, _params in _function_scopes(ctx.tree):
            # Most recent binding per name, in source order: a name counts
            # as dict-valued at a call site only if its LAST assignment
            # before that line was a dict display/comp/dict() call — so
            # the normalize-then-pass idiom stays silent.
            bindings: list = []  # (lineno, name, is_dict)
            calls: list = []
            for node in _walk_no_nested_defs(body):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        for name in _target_names(t):
                            bindings.append(
                                (node.lineno, name, _is_dict_expr(node.value))
                            )
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    name = dotted_name(node.target)
                    if name:
                        bindings.append(
                            (node.lineno, name, _is_dict_expr(node.value))
                        )
                elif isinstance(node, ast.Call):
                    calls.append(node)

            for call in calls:
                if _tail(dotted_name(call.func)) in self._ALLOWED_CALLEES:
                    continue
                for kw in call.keywords:
                    if kw.arg != "width_overrides":
                        continue
                    value = kw.value
                    verdict = None
                    if _is_dict_expr(value):
                        verdict = "a dict literal"
                    elif isinstance(value, ast.Name):
                        prior = [
                            b
                            for b in bindings
                            if b[1] == value.id and b[0] <= call.lineno
                        ]
                        if prior and max(prior)[2]:
                            verdict = f"'{value.id}', last assigned a dict"
                    if verdict:
                        yield ctx.finding(
                            self,
                            call,
                            f"width_overrides receives {verdict} — flax "
                            "Modules are hashed into the jit trace cache, "
                            "so this raises TypeError: unhashable at the "
                            "first jitted apply; pass "
                            "tuple(sorted(d.items())) (create_model "
                            "normalizes internally and is exempt)",
                        )
