"""graftlint — JAX-aware static analysis for this repo.

``python -m turboprune_tpu.analysis [paths]`` runs eight per-file rules
tuned to the failure modes that sink JAX/TPU training and serving stacks:
host syncs inside jit, trace-cache-defeating jit construction,
static_argnames typos, PRNG key reuse, rank-conditional collectives,
donated-buffer reads, silent broad excepts, and debug output in compiled
code.

``--project`` (PR 3) grows that into a whole-project analyzer: a symbol
table + call graph (project.py, callgraph.py) lets five of those rules
fire THROUGH call chains — the ``np.asarray`` three helpers below a
jitted step, the collective buried under ``if is_primary():`` via a
checkpoint wrapper, the key consumed twice through a sampler in another
module — each finding carrying the call-path trace that justifies it.
The same mode statically cross-checks every ``conf/**/*.yaml`` against
the schema dataclasses (conf_rules.py): unknown keys, choice-set and
type violations, broken ``defaults:`` entries, duplicate keys, and
schema fields nothing ever reads.

Findings are waived inline with ``# graftlint: disable=<rule> -- reason``
(YAML comments included) and the whole package + conf is kept at zero
unwaived findings by tests/test_analysis.py's self-gate.

Deliberately jax-free: importing this package must work on any machine
(pre-commit, CI sandboxes) without an accelerator stack. Importing
``rules`` registers the rule set as a side effect.
"""

from .core import (  # noqa: F401
    AnalysisResult,
    Finding,
    ModuleContext,
    RULES,
    Rule,
    Waiver,
    analyze_files,
    analyze_paths,
    analyze_project,
    analyze_source,
    is_test_file,
    register,
)
from . import rules  # noqa: F401  (registers the rule set)
from . import dtype_rules  # noqa: F401  (registers the dtype-flow rules)
from . import concurrency_rules  # noqa: F401  (registers the thread rules)
from . import shape_rules  # noqa: F401  (registers the shape-flow rules)
from .conf_rules import CONF_RULES  # noqa: F401
from .reporters import render_json, render_sarif, render_text  # noqa: F401

__all__ = [
    "AnalysisResult",
    "CONF_RULES",
    "Finding",
    "ModuleContext",
    "RULES",
    "Rule",
    "Waiver",
    "analyze_files",
    "analyze_paths",
    "analyze_project",
    "analyze_source",
    "is_test_file",
    "register",
    "render_json",
    "render_sarif",
    "render_text",
]
