"""graftlint — JAX-aware static analysis for this repo.

``python -m turboprune_tpu.analysis [paths]`` runs eight rules tuned to
the failure modes that sink JAX/TPU training and serving stacks: host
syncs inside jit, trace-cache-defeating jit construction, static_argnames
typos, PRNG key reuse, rank-conditional collectives, donated-buffer
reads, silent broad excepts, and debug output in compiled code. Findings
are waived inline with ``# graftlint: disable=<rule> -- reason`` and the
whole package is kept at zero unwaived findings by
tests/test_analysis.py's self-gate.

Deliberately jax-free: importing this package must work on any machine
(pre-commit, CI sandboxes) without an accelerator stack. Importing
``rules`` registers the rule set as a side effect.
"""

from .core import (  # noqa: F401
    AnalysisResult,
    Finding,
    ModuleContext,
    RULES,
    Rule,
    Waiver,
    analyze_paths,
    analyze_source,
    is_test_file,
    register,
)
from . import rules  # noqa: F401  (registers the rule set)
from .reporters import render_json, render_text  # noqa: F401

__all__ = [
    "AnalysisResult",
    "Finding",
    "ModuleContext",
    "RULES",
    "Rule",
    "Waiver",
    "analyze_paths",
    "analyze_source",
    "is_test_file",
    "register",
    "render_json",
    "render_text",
]
