"""Shape-flow rules: the static half of the executable-surface contract.

Five rules over the lattice/interpreter in shape_flow.py, all tuned to the
ways a shape can silently blow up (or silently collapse) the set of XLA
executables this repo promises is finite:

* ``shape-varying-jit-arg`` — a loop-varying or data-dependent dim reaches
  a jitted callable with no pad/bucket site on the path: one compile per
  distinct value, the classic recompile-per-iteration. Dims drawn from a
  literal bucket table (``b = BUCKETS[i]``) are bounded and stay silent.
* ``concrete-shape-branch`` — a Python ``if``/``while`` on a traced dim
  inside a jit region. Legal (shapes are concrete at trace time) but each
  shape class now traces a DIFFERENT program: the executable set fans out
  per branch, invisibly to any bucket declaration.
* ``bucket-set-escape`` — a bucket literal at an engine/batcher call site
  that is not a member of the module's declared bucket set: the executable
  it compiles exists outside every manifest, warmup loop, and pre-warm.
* ``unpinned-donation-shape`` — a donated argument of a jitted callable
  whose inferred shape differs across call sites: donation binds
  per-executable, so every new shape is a new compile AND the buffer
  reuse the donation promised silently stops happening.
* ``rank-change-into-cache`` — a reshape/squeeze-produced array feeding a
  keyed executable cache whose key uses a single dim (``x.shape[0]``)
  without the rank: a (8,) and an (8, 1) collide on the same key and the
  cache serves the wrong executable.

In project mode ``concrete-shape-branch`` also fires through call chains:
a helper reachable from a jit entry is analyzed with its params seeded as
traced arrays, findings carrying the call path — same shape as
dtype_rules.dtype_project_findings. All five rules skip test files (tests
flex shapes on purpose) and only fire when the lattice KNOWS the hazard,
so ``?`` stays silent rather than noisy.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterator, Optional

from .core import ModuleContext, Rule, register
from .regions import donation_spec, dotted_name, is_jit_wrapper, param_names
from .shape_flow import (
    ArrayVal,
    DimVal,
    ScopeShapes,
    ShapeTupleVal,
    dim_known,
)

__all__ = [
    "ShapeVaryingJitArgRule",
    "ConcreteShapeBranchRule",
    "BucketSetEscapeRule",
    "UnpinnedDonationShapeRule",
    "RankChangeIntoCacheRule",
    "shape_project_findings",
]


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


# ------------------------------------------------- shared: jitted callables


def _module_jitted(ctx: ModuleContext) -> dict:
    """Callable-name -> (positional params or None) for jitted callables
    visible in this module: decorated defs plus ``g = jax.jit(f)``."""
    jitted: dict = {}
    defs = {
        n.name: n
        for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for region in ctx.jit_regions:
        node = region.node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and region.reason.startswith("@"):
            jitted[node.name] = param_names(node)
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and is_jit_wrapper(node.value.func)
            and node.value.args
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            fn_arg = node.value.args[0]
            params = None
            if isinstance(fn_arg, ast.Name) and fn_arg.id in defs:
                params = param_names(defs[fn_arg.id])
            jitted[node.targets[0].id] = params
    return jitted


# --------------------------------------------------- shape-varying-jit-arg

_PAD_SITE_MARKERS = ("pad", "bucket", "clamp")


def _has_pad_site(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            tail = _tail(dotted_name(node.func)) or ""
            if any(m in tail.lower() for m in _PAD_SITE_MARKERS):
                return True
    return False


def _names_in(expr: ast.AST) -> set:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _literal_int_seq(node: ast.AST) -> bool:
    return isinstance(node, (ast.Tuple, ast.List)) and bool(node.elts) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, int)
        for e in node.elts
    )


def _slice_varying(expr: ast.AST, varying: set) -> Optional[ast.AST]:
    """First Subscript in ``expr`` whose slice bound references a varying
    name — the syntactic site where a loop-varying dim is cut."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Subscript):
            continue
        parts = (
            node.slice.elts if isinstance(node.slice, ast.Tuple) else [node.slice]
        )
        for p in parts:
            if isinstance(p, ast.Slice):
                for bound in (p.lower, p.upper, p.step):
                    if bound is not None and _names_in(bound) & varying:
                        return node
    return None


@register
class ShapeVaryingJitArgRule(Rule):
    id = "shape-varying-jit-arg"
    severity = "warning"
    skip_in_tests = True
    description = (
        "loop-varying or data-dependent dim reaches a jitted callable with "
        "no pad/bucket site on the path — one XLA compile per distinct "
        "value (recompile-per-iteration)"
    )
    doc_why = (
        "A jit executable is specialized per shape: slicing `x[:n]` with a "
        "loop-varying `n` compiles every iteration, turning a microseconds "
        "dispatch into seconds of XLA work. Pad to a declared bucket "
        "(serve/batcher.py) so the executable set stays finite."
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        jitted = _module_jitted(ctx)
        if not jitted:
            return
        # literal int tables in scope: names whose subscript is a BOUNDED
        # draw (b = BUCKETS[i] stays silent)
        tables = {
            t.id
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Assign) and _literal_int_seq(node.value)
            for t in node.targets
            if isinstance(t, ast.Name)
        }
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            yield from self._check_loop(ctx, loop, jitted, tables)

    def _check_loop(
        self, ctx: ModuleContext, loop: ast.AST, jitted: dict, tables: set
    ) -> Iterator:
        bindings: dict = {}  # name -> last RHS expr assigned in the loop body
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                bindings[node.targets[0].id] = node.value

        varying: set = set()
        if isinstance(loop, ast.For):
            varying |= _names_in(loop.target)
        else:
            # while: loop-carried names (assigned from an expression that
            # reads a name also assigned in the body)
            assigned = set(bindings)
            varying |= {
                n for n, v in bindings.items() if _names_in(v) & assigned
            }
        for _ in range(2):  # fixpoint over intra-loop derivations
            for name, value in bindings.items():
                if name in varying or not (_names_in(value) & varying):
                    continue
                if _has_pad_site(value):
                    continue  # padded/bucketed: bounded by construction
                if (
                    isinstance(value, ast.Subscript)
                    and isinstance(value.value, ast.Name)
                    and value.value.id in tables
                ):
                    continue  # drawn from a literal int table: bounded
                varying.add(name)
        if not varying:
            return

        for call in ast.walk(loop):
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id in jitted
            ):
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            for arg in args:
                expr = arg
                if isinstance(arg, ast.Name) and arg.id in bindings:
                    expr = bindings[arg.id]
                if _has_pad_site(expr):
                    continue
                sub = _slice_varying(expr, varying)
                if sub is None:
                    continue
                names = sorted(_names_in(sub) & varying) or sorted(varying)
                yield ctx.finding(
                    self,
                    call,
                    f"jitted {call.func.id}() receives an argument sliced "
                    f"by loop-varying {', '.join(names)!s} — every distinct "
                    "value is a fresh XLA compile; pad to a declared bucket "
                    "(or draw the dim from a literal bucket table) so the "
                    "executable set stays finite",
                )
                break


# --------------------------------------------------- concrete-shape-branch


def _branch_scan(
    rule: Rule,
    ctx: ModuleContext,
    root: ast.AST,
    sd: ScopeShapes,
    traced: frozenset,
    why: str,
    trace_fn: Optional[Callable] = None,
) -> Iterator:
    for node in ast.walk(root):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if isinstance(node, ast.If) and not node.orelse and all(
            isinstance(s, ast.Raise) for s in node.body
        ):
            # a shape GUARD (body only raises) doesn't fan out the
            # executable set: both classes fail at trace or run one program
            continue
        dep = _dim_dependency(node.test, sd, traced)
        if dep is None:
            continue
        kind = "if" if isinstance(node, ast.If) else "while"
        yield ctx.finding(
            rule,
            node,
            f"Python `{kind}` on a dim of traced {dep!r} inside a jit "
            f"region ({why}): each shape class traces a DIFFERENT program, "
            "so the executable set fans out per branch, invisibly to any "
            "bucket declaration; hoist the branch to the bucketing site or "
            "use lax.cond on a traced value",
            trace=trace_fn(node) if trace_fn else None,
        )


def _dim_dependency(
    test: ast.AST, sd: ScopeShapes, traced: frozenset
) -> Optional[str]:
    """Name of the traced array whose dim the test depends on, if any."""
    for node in ast.walk(test):
        v = sd.value_of(node)
        if isinstance(v, (DimVal, ShapeTupleVal)) and v.src in traced:
            return v.src
    return None


@register
class ConcreteShapeBranchRule(Rule):
    id = "concrete-shape-branch"
    severity = "warning"
    skip_in_tests = True
    description = (
        "Python if/while on a traced dim inside a jit region — each shape "
        "class traces a different program (executable fan-out per branch)"
    )
    doc_why = (
        "Shapes are concrete at trace time, so the branch runs — but each "
        "shape class now compiles a DIFFERENT executable, multiplying the "
        "compile surface behind the bucket set's back. The manifest can "
        "only bound what doesn't branch on shape inside jit."
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        for region in ctx.jit_regions:
            traced = region.traced_params
            if not traced:
                continue
            sd = ScopeShapes(
                region.node, seed={p: ArrayVal(None, p) for p in traced}
            )
            yield from _branch_scan(
                self, ctx, region.node, sd, traced, region.reason
            )


# ------------------------------------------------------- bucket-set-escape

_BUCKET_CALL_TAILS = {"_executable", "warmup_bucket", "compile_bucket"}


@register
class BucketSetEscapeRule(Rule):
    id = "bucket-set-escape"
    severity = "error"
    skip_in_tests = True
    description = (
        "bucket literal at an engine/cache call site that is not in the "
        "module's declared bucket set — compiles an executable outside "
        "every manifest and warmup"
    )
    doc_why = (
        "Warmup, the AOT cache, blue/green pre-warm, and the exec manifest "
        "all enumerate the DECLARED buckets; a stray literal compiles lazily "
        "at first traffic instead — exactly the latency spike bucketing "
        "exists to prevent."
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        declared: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _literal_int_seq(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name) and "bucket" in t.id.lower():
                        declared.update(e.value for e in node.value.elts)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "buckets" and _literal_int_seq(kw.value):
                        declared.update(e.value for e in kw.value.elts)
        if not declared:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            lit = None
            for kw in node.keywords:
                if kw.arg == "bucket" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int):
                    lit = kw.value.value
            tail = _tail(dotted_name(node.func))
            if (
                lit is None
                and tail in _BUCKET_CALL_TAILS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, int)
            ):
                lit = node.args[0].value
            if lit is not None and lit not in declared:
                yield ctx.finding(
                    self,
                    node,
                    f"bucket {lit} is not in this module's declared bucket "
                    f"set {tuple(sorted(declared))} — the executable it "
                    "compiles exists outside every manifest, warmup loop "
                    "and pre-warm; add it to the declaration or draw from it",
                )


# ------------------------------------------------ unpinned-donation-shape


def _donation_kwargs(call: ast.Call) -> Optional[tuple]:
    """donation_spec without the jit-wrapper check on the callee — for
    ``@partial(jax.jit, donate_argnums=...)`` where the outer call is
    ``partial`` but the jit wrapper is its first argument."""
    from .regions import literal_str_seq

    nums, names = [], []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            nums.extend(
                e.value
                for e in elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            )
        elif kw.arg == "donate_argnames":
            names.extend(literal_str_seq(kw.value) or [])
    return (tuple(nums), tuple(names)) if (nums or names) else None


def _decorator_donation(node) -> Optional[tuple]:
    for dec in getattr(node, "decorator_list", ()):
        if not isinstance(dec, ast.Call):
            continue
        if is_jit_wrapper(dec.func):
            spec = donation_spec(dec)
            if spec is not None:
                return spec
        elif dec.args and is_jit_wrapper(dec.args[0]):
            spec = _donation_kwargs(dec)
            if spec is not None:
                return spec
    return None


@register
class UnpinnedDonationShapeRule(Rule):
    id = "unpinned-donation-shape"
    severity = "warning"
    skip_in_tests = True
    description = (
        "donated arg of a jitted callable gets different known shapes at "
        "different call sites — each shape is a fresh executable and the "
        "donation silently stops holding"
    )
    doc_why = (
        "Donation binds buffers per-executable. A donated arg whose shape "
        "varies across call sites recompiles per shape AND quietly loses "
        "the in-place buffer reuse the donation promised — double memory "
        "at exactly the sites that opted into saving it."
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        defs = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        donating: dict = {}  # callable name -> (params, donated positions)
        for name, fn in defs.items():
            spec = _decorator_donation(fn)
            if spec is not None:
                argnums, argnames = spec
                params = param_names(fn)
                slots = set(argnums) | {
                    params.index(a) for a in argnames if a in params
                }
                if slots:
                    donating[name] = (params, slots)
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and is_jit_wrapper(node.value.func)
                and node.value.args
                and isinstance(node.value.args[0], ast.Name)
                and node.value.args[0].id in defs
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                spec = donation_spec(node.value)
                if spec is None:
                    continue
                argnums, argnames = spec
                params = param_names(defs[node.value.args[0].id])
                slots = set(argnums) | {
                    params.index(a) for a in argnames if a in params
                }
                if slots:
                    donating[node.targets[0].id] = (params, slots)
        if not donating:
            return

        sd = ScopeShapes(ctx.tree)
        sites: dict = {}  # (callable, slot) -> {shape: first call node}
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in donating
            ):
                continue
            params, slots = donating[node.func.id]
            for i, arg in enumerate(node.args):
                if i not in slots:
                    continue
                v = sd.value_of(arg)
                if not (
                    isinstance(v, ArrayVal)
                    and v.shape is not None
                    and all(dim_known(d) for d in v.shape)
                ):
                    continue
                sites.setdefault((node.func.id, i), {}).setdefault(
                    v.shape, node
                )
        for (fname, slot), by_shape in sites.items():
            if len(by_shape) < 2:
                continue
            nodes = sorted(by_shape.items(), key=lambda kv: kv[1].lineno)
            (s0, first), (s1, second) = nodes[0], nodes[1]
            yield ctx.finding(
                self,
                second,
                f"donated arg {slot} of jitted {fname}() is {s0} at line "
                f"{first.lineno} but {s1} here — each distinct shape is a "
                "fresh executable and the donation no longer reuses the "
                "buffer; pin the shape (pad/bucket) or drop the donation",
            )


# ------------------------------------------------ rank-change-into-cache

_RANK_CHANGE_TAILS = {
    "reshape", "squeeze", "expand_dims", "ravel", "flatten",
    "atleast_1d", "atleast_2d", "atleast_3d",
}
_CACHE_NAME_MARKERS = ("cache", "compiled", "executable")


def _is_cache_name(name: Optional[str]) -> bool:
    return bool(name) and any(m in name.lower() for m in _CACHE_NAME_MARKERS)


def _dim_only_key_names(key: ast.AST, rank_changed: set) -> set:
    """Rank-changed names whose SINGLE dim keys the expression, with no
    rank witness (whole ``.shape``, ``.ndim``, ``len()``) beside it."""
    dim_names: set = set()
    rank_witness = False
    subscripted_shapes: set = set()
    for node in ast.walk(key):
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Attribute
        ) and node.value.attr == "shape" and isinstance(
            node.value.value, ast.Name
        ):
            subscripted_shapes.add(id(node.value))
            if node.value.value.id in rank_changed:
                dim_names.add(node.value.value.id)
    for node in ast.walk(key):
        if isinstance(node, ast.Attribute):
            if node.attr == "ndim":
                rank_witness = True
            elif node.attr == "shape" and id(node) not in subscripted_shapes:
                rank_witness = True  # whole shape tuple in the key
        elif isinstance(node, ast.Call) and dotted_name(node.func) == "len":
            rank_witness = True
    return set() if rank_witness else dim_names


@register
class RankChangeIntoCacheRule(Rule):
    id = "rank-change-into-cache"
    severity = "warning"
    skip_in_tests = True
    description = (
        "reshape/squeeze-produced array keys an executable cache by a "
        "single dim without the rank — different-rank arrays collide on "
        "one key and the wrong executable is served"
    )
    doc_why = (
        "An (8,) and an (8, 1) agree on shape[0] but compile different "
        "programs; keyed only by the dim, the second lookup silently "
        "returns the first's executable. Key by the full shape tuple (as "
        "serve/fleet/aot_cache.py does) or include the rank."
    )

    def check(self, ctx: ModuleContext) -> Iterator:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            rank_changed: set = set()
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                ):
                    tail = _tail(dotted_name(node.value.func))
                    if tail in _RANK_CHANGE_TAILS:
                        rank_changed.add(node.targets[0].id)
            if not rank_changed:
                continue
            for node in ast.walk(fn):
                key = None
                if isinstance(node, ast.Subscript) and _is_cache_name(
                    dotted_name(node.value)
                ):
                    key = node.slice
                elif isinstance(node, ast.Call) and _tail(
                    dotted_name(node.func)
                ) == "make_key":
                    parts = list(node.args) + [kw.value for kw in node.keywords]
                    key = ast.Tuple(elts=parts, ctx=ast.Load()) if parts else None
                if key is None:
                    continue
                hits = _dim_only_key_names(key, rank_changed)
                if hits:
                    name = sorted(hits)[0]
                    yield ctx.finding(
                        self,
                        node,
                        f"executable cache keyed by a single dim of "
                        f"{name!r}, which was rank-changed above — arrays "
                        "of different rank with the same dim collide on "
                        "this key and the wrong executable is served; key "
                        "by the full shape tuple (or include the rank)",
                    )


# ------------------------------------------------------- project layer


def shape_project_findings(graph, contexts: dict) -> Iterator:
    """concrete-shape-branch through call chains: a helper reachable from
    any jit entry is analyzed with its params seeded as traced arrays (the
    entry passes its traced values on), findings carrying the call path.
    Helpers that are themselves lexical regions are the per-file pass's
    job and are skipped, mirroring dtype_rules.dtype_project_findings."""
    from .callgraph import MAX_DEPTH, _fmt
    from .core import RULES

    rule = RULES["concrete-shape-branch"]

    lexical_nodes = {
        id(r.node)
        for regions in graph.regions_by_module.values()
        for r in regions
    }
    entries: list = []
    for regions in graph.regions_by_module.values():
        for region in regions:
            fi = graph.index.function_for_node(region.node)
            if fi is not None:
                entries.append((fi, region.reason))

    reach: dict = {}  # qualname -> (why, trace hops)
    frontier = []
    for fi, reason in entries:
        if fi.qualname not in reach:
            reach[fi.qualname] = (
                reason,
                [f"jit entry {_fmt(fi)} [{reason}]"],
            )
            frontier.append(fi)
    depth = 0
    while frontier and depth < MAX_DEPTH:
        depth += 1
        nxt = []
        for fi in frontier:
            why, trace = reach[fi.qualname]
            for callee, line in graph.edges.get(fi.qualname, ()):
                if callee.qualname in reach:
                    continue
                reach[callee.qualname] = (
                    why,
                    trace + [f"{_fmt(callee)} called at line {line}"],
                )
                nxt.append(callee)
        frontier = nxt

    entry_quals = {fi.qualname for fi, _ in entries}
    for qual, (why, trace) in reach.items():
        if qual in entry_quals:
            continue
        fi = graph.index.functions.get(qual)
        if fi is None or id(fi.node) in lexical_nodes:
            continue
        ctx = contexts.get(fi.path)
        if ctx is None:
            continue
        traced = frozenset(p for p in fi.params if p != "self")
        if not traced:
            continue
        sd = ScopeShapes(
            fi.node, seed={p: ArrayVal(None, p) for p in traced}
        )

        def trace_fn(node, _fi=fi, _trace=trace):
            return _trace + [f"{_fi.name} ({_fi.path}:{node.lineno})"]

        yield from _branch_scan(
            rule, ctx, fi.node, sd, traced, f"{why}, via caller", trace_fn
        )
