"""Thread-model discovery over the project symbol table.

Which code runs on which thread is, like "inside jit", a dynamic property
that this codebase keeps lexically decidable: every worker thread is
spawned by ``threading.Thread(target=self._run, ...)``,
``threading.Timer(t, fn)``, or ``pool.submit(self._flush, ...)`` on a
declared ``ThreadPoolExecutor``. This module finds those spawn sites,
resolves the targets through project.py, and computes two closures the
concurrency rules consume:

* **worker closure** — for each function, which spawn targets can reach it
  through direct calls (with the call path, for finding traces). Edges
  here are DIRECT calls only — deliberately narrower than callgraph.py,
  whose callback edges ("passed as an argument, assumed invoked") would
  make every spawn target caller-reachable through its own spawn site.
* **caller reachability** — whether the function can also run on an
  external caller's thread: the fixpoint seeded by functions with no
  in-edges (API surface: nothing in the project calls them, so only
  external callers do) and by module-scope calls, propagated along direct
  calls. A spawn target is caller-reachable only if something also CALLS
  it directly.

Unresolvable targets (``pool.submit(task)`` where ``task`` is a local
closure, lambdas, stdlib callables like ``server.serve_forever``) are
skipped: the model under-approximates, the rules stay silent there, and
the runtime sanitizer (sanitizer.py) exists precisely to catch what this
lexical model cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .locks import DeclaredTypes, collect_declared_types, ctor_kind
from .project import FunctionInfo, ModuleInfo, ProjectIndex
from .regions import dotted_name, unwrap_partial
from .rules import _own_statements, _root, _tail, _walk_no_nested_defs

__all__ = ["ThreadEntry", "ThreadModel"]

_POOL_NAME_HINTS = ("pool", "executor", "workers")
CALLER = "<caller>"


@dataclasses.dataclass
class ThreadEntry:
    """One spawn site: a project function handed to a thread/timer/pool."""

    qualname: str  # the target function
    kind: str  # "thread" | "timer" | "pool"
    spawner: str  # qualname of the spawning function, or "<module ...>"
    file: str
    line: int

    @property
    def label(self) -> str:
        noun = {
            "thread": "thread",
            "timer": "timer thread",
            "pool": "pool worker",
        }[self.kind]
        name = self.qualname.rsplit(".", 1)[-1]
        return f"{noun} {name}() [spawned at {self.file}:{self.line}]"


class ThreadModel:
    def __init__(
        self, index: ProjectIndex, types: Optional[DeclaredTypes] = None
    ):
        self.index = index
        self.types = types or collect_declared_types(index)
        self.entries: dict = {}  # target qualname -> [ThreadEntry]
        self.edges: dict = {}  # caller qualname -> [(callee qualname, line)]
        self.worker_paths: dict = {}  # func -> {target: ((caller, callee, line), ...)}
        self.caller_reachable: set = set()
        self.spawning_classes: set = set()  # "mod.Class"
        self._module_called: set = set()
        self._build()

    # -------------------------------------------------------------- queries
    def worker_targets(self, qualname: str) -> list:
        """Spawn targets whose closure contains this function, sorted."""
        return sorted(self.worker_paths.get(qualname, ()))

    def contexts(self, qualname: str) -> set:
        """Execution contexts: spawn-target qualnames plus CALLER."""
        out = set(self.worker_paths.get(qualname, ()))
        if qualname in self.caller_reachable:
            out.add(CALLER)
        return out

    def is_pool_target(self, target: str) -> bool:
        return any(e.kind == "pool" for e in self.entries.get(target, ()))

    def context_label(self, context: str) -> str:
        if context == CALLER:
            return "the caller's thread"
        entries = self.entries.get(context)
        if entries:
            return entries[0].label
        return context

    def trace_to(self, qualname: str, target: str) -> list:
        """Human-readable hops: spawn site -> ... -> function."""
        entries = self.entries.get(target, ())
        hops = [f"spawned: {entries[0].label}"] if entries else []
        for caller, callee, line in self.worker_paths.get(qualname, {}).get(
            target, ()
        ):
            cfi = self.index.functions.get(caller)
            loc = f"{cfi.path}:{line}" if cfi else str(line)
            hops.append(f"{_short(caller)} calls {_short(callee)} ({loc})")
        return hops

    # ------------------------------------------------------------- building
    def _build(self) -> None:
        index = self.index
        for mi in index.modules.values():
            local_fns = sorted(
                (
                    fi
                    for fi in index.functions.values()
                    if fi.path == mi.path
                ),
                key=lambda f: f.qualname,
            )
            scopes = [(None, mi.tree.body)]
            scopes.extend((fi, fi.node.body) for fi in local_fns)
            for scope, body in scopes:
                local_pools = _local_pool_names(body)
                for node in _walk_no_nested_defs(_own_statements(body)):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = index.resolve_call(mi, node.func, scope)
                    if callee is not None:
                        if scope is not None:
                            self.edges.setdefault(
                                scope.qualname, []
                            ).append((callee.qualname, node.lineno))
                        else:
                            self._module_called.add(callee.qualname)
                    self._scan_spawn(node, mi, scope, local_pools)
        self._close_workers()
        self._close_callers()

    def _scan_spawn(self, node, mi, scope, local_pools) -> None:
        name = dotted_name(node.func) or ""
        tail = _tail(name)
        target_expr = None
        kind = None
        if tail == "Thread" and _root(name) in ("threading", "Thread"):
            kind = "thread"
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif tail == "Timer" and _root(name) in ("threading", "Timer"):
            kind = "timer"
            if len(node.args) >= 2:
                target_expr = node.args[1]
            for kw in node.keywords:
                if kw.arg == "function":
                    target_expr = kw.value
        elif tail == "submit" and isinstance(node.func, ast.Attribute):
            if self._is_pool(node.func.value, scope, local_pools):
                kind = "pool"
                if node.args:
                    target_expr = node.args[0]
        if kind is None or target_expr is None:
            return
        target = self.index.resolve_call(
            mi, unwrap_partial(target_expr), scope
        )
        if target is None:
            return
        spawner = (
            scope.qualname if scope else f"<module {mi.modname}>"
        )
        entry = ThreadEntry(
            qualname=target.qualname,
            kind=kind,
            spawner=spawner,
            file=mi.path,
            line=node.lineno,
        )
        self.entries.setdefault(target.qualname, []).append(entry)
        for fi in (scope, target):
            if fi is not None and fi.class_name:
                self.spawning_classes.add(f"{fi.modname}.{fi.class_name}")

    def _is_pool(self, receiver, scope, local_pools) -> bool:
        rname = dotted_name(receiver) or ""
        parts = rname.split(".")
        if (
            parts
            and parts[0] == "self"
            and len(parts) == 2
            and scope is not None
            and scope.class_name
        ):
            cq = f"{scope.modname}.{scope.class_name}"
            if self.types.attr_kind(cq, parts[1]) == "pool":
                return True
        if len(parts) == 1 and parts[0] in local_pools:
            return True
        return bool(rname) and any(
            h in rname.lower() for h in _POOL_NAME_HINTS
        )

    def _close_workers(self) -> None:
        for target in sorted(self.entries):
            frontier = [(target, ())]
            seen = {target}
            self.worker_paths.setdefault(target, {})[target] = ()
            while frontier:
                qual, path = frontier.pop(0)
                for callee, line in self.edges.get(qual, ()):
                    if callee in seen:
                        continue
                    seen.add(callee)
                    hop = path + ((qual, callee, line),)
                    self.worker_paths.setdefault(callee, {})[target] = hop
                    frontier.append((callee, hop))

    def _close_callers(self) -> None:
        targets = set(self.entries)
        in_deg: dict = {q: 0 for q in self.index.functions}
        for caller, outs in self.edges.items():
            for callee, _line in outs:
                if callee in in_deg:
                    in_deg[callee] += 1
        roots = {
            q
            for q, d in in_deg.items()
            if d == 0 and q not in targets
        }
        roots |= self._module_called - targets
        reach = set(roots)
        frontier = sorted(roots)
        while frontier:
            qual = frontier.pop()
            for callee, _line in self.edges.get(qual, ()):
                if callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)
        self.caller_reachable = reach


def _short(qualname: str) -> str:
    parts = qualname.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else qualname


def _local_pool_names(body) -> set:
    """Names bound to a pool constructor inside one scope body, including
    ``with ThreadPoolExecutor(...) as pool:``."""
    out: set = set()
    for node in _walk_no_nested_defs(_own_statements(body)):
        if isinstance(node, ast.Assign) and ctor_kind(node.value) == "pool":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if (
                    ctor_kind(item.context_expr) == "pool"
                    and isinstance(item.optional_vars, ast.Name)
                ):
                    out.add(item.optional_vars.id)
    return out
