"""``--compile-audit``: every runtime XLA compile attributed to the
static executable manifest — the runtime mirror of exec_manifest.py,
exactly as jaxpr_audit.py mirrors the dtype rules and sanitizer.py the
thread rules.

The manifest claims the compile surface is finite and statically known.
This mode checks the claim against what XLA actually does: it patches the
one funnel every compile goes through (``jax._src.compiler
.backend_compile``), drives the package's real compile-heavy subsystems
(the serving engine's bucket warmup; the synthetic train step), and
demands that every compile observed in the measured window is attributed
to a manifest entry or compile site:

* by NAME — a compiled module is named ``jit_<fn.__name__>`` (non-word
  characters mangled to ``_``), so ``jit__apply`` attributes to the
  ``jax.jit(self._apply)`` compile site and ``jit_train_step`` to the
  mesh factories' ``train_step`` target;
* by SITE — failing that, the innermost package stack frame under the
  compile must sit inside a manifest entry's span or on a compile-site
  line.

A compile neither explains is an executable the static layer never
enumerated — the exact hazard the shape rules exist to prevent (bucket
escapes, data-dependent shapes) — and fails the run. The serving driver
additionally checks that every bucket it compiled and its plan kind are
``covers()``-ed by the manifest, tying the runtime AOT cache key
vocabulary to the static declaration.

Driver discipline: all setup (model init, mask folding, array literals)
happens OUTSIDE the ledger window — eager jnp ops compile tiny modules
(``jit_iota``, ...) that are infrastructure, not part of the serving
surface. The measured window contains only the steady-state paths whose
compile behavior the manifest bounds.

jax imports live inside functions; the package stays importable with no
accelerator stack. Exit codes follow the CLI contract: 0 clean, 1
unattributed compile / uncovered bucket, 2 usage or environment error.
"""

from __future__ import annotations

import re
import threading
import traceback
from pathlib import Path
from typing import Callable, Optional

from .drivers import default_step_entry, resolve_runtime_target
from .exec_manifest import covers, executable_names, load_manifest

__all__ = ["AuditError", "CompileLedger", "run_compile_audit"]

_PKG_ROOT = Path(__file__).resolve().parents[1]
_ANALYSIS_DIR = Path(__file__).resolve().parent


class AuditError(RuntimeError):
    """Usage/environment error (CLI maps it to exit code 2)."""


def _runtime_name(fn_name: str) -> str:
    """The MLIR module name jax gives a compiled ``fn_name`` — e.g.
    ``<lambda>`` becomes ``jit__lambda_``."""
    return "jit_" + re.sub(r"\W", "_", fn_name)


def _module_name(module) -> str:
    try:
        attr = module.operation.attributes["sym_name"]
        value = getattr(attr, "value", None)
        return str(value) if value is not None else str(attr).strip('"')
    except Exception:  # graftlint: disable=broad-except -- MLIR binding drift degrades to "?", which the report shows as unattributed
        return "?"


def _repo_site() -> Optional[tuple]:
    """Innermost package frame (outside analysis/) on the current stack:
    the repo line that triggered this compile."""
    for frame in reversed(traceback.extract_stack()):
        p = Path(frame.filename)
        try:
            p.relative_to(_ANALYSIS_DIR)
            continue  # the audit's own frames don't attribute anything
        except ValueError:
            pass
        try:
            p.relative_to(_PKG_ROOT)
        except ValueError:
            continue
        return str(p), frame.lineno
    return None


class CompileLedger:
    """Context manager: patch ``backend_compile``, record every compile
    in the window as ``{"name", "site"}`` (site = innermost repo frame).
    Thread-safe — the serving engine compiles under its own lock, and
    nothing stops a driver from compiling from several threads."""

    def __init__(self):
        self.records: list = []
        self._mu = threading.Lock()
        self._orig = None
        self._host = None

    def _patch_point(self):
        import jax._src.compiler as compiler

        if hasattr(compiler, "backend_compile"):
            return compiler
        import jax._src.dispatch as dispatch  # older jax

        if hasattr(dispatch, "backend_compile"):
            return dispatch
        raise AuditError(
            "cannot find jax's backend_compile to patch (jax internals "
            "moved); --compile-audit needs updating for this jax version"
        )

    def __enter__(self) -> "CompileLedger":
        host = self._patch_point()
        orig = host.backend_compile
        ledger = self

        def patched(*args, **kwargs):
            module = next(
                (
                    a
                    for a in list(args) + list(kwargs.values())
                    if hasattr(a, "operation")
                ),
                None,
            )
            rec = {
                "name": _module_name(module) if module is not None else "?",
                "site": _repo_site(),
            }
            with ledger._mu:
                ledger.records.append(rec)
            return orig(*args, **kwargs)

        host.backend_compile = patched
        self._host, self._orig = host, orig
        return self

    def __exit__(self, *exc) -> None:
        if self._host is not None:
            self._host.backend_compile = self._orig
            self._host = self._orig = None


def _attribution(rec: dict, names: set, spans: list) -> Optional[str]:
    """How the manifest explains one compile record, or None."""
    for n in names:
        if rec["name"] == _runtime_name(n):
            return f"name match: {n}"
    site = rec["site"]
    if site is not None:
        file, line = site
        rel = _posix_rel(file)
        for sfile, start, end, label in spans:
            if rel == sfile and start <= line <= end:
                return f"site match: {label} at {sfile}:{start}"
    return None


def _posix_rel(path: str) -> str:
    p = Path(path).resolve()
    try:
        return p.relative_to(_PKG_ROOT.parent).as_posix()
    except ValueError:
        return p.as_posix()


def _manifest_spans(manifest: dict) -> list:
    """(file, start, end, label) windows a triggering repo frame may sit
    in. Compile-site lines get a small slop: the jit call and the
    ``.lower()``/``.compile()`` it feeds span a few lines."""
    spans = []
    for e in manifest.get("entries", ()):
        spans.append((e["file"], e["line"], e["end"], f"entry {e['name']}"))
    for s in manifest.get("compile_sites", ()):
        spans.append(
            (s["file"], s["line"], s["line"] + 20, f"site {s['target']}")
        )
    return spans


# ------------------------------------------------------------------ drivers


def _drive_serve(ledger: CompileLedger, manifest: dict) -> list:
    """A real InferenceEngine over a fresh (all-ones-masked) checkpoint:
    warmup compiles every bucket, predict must then compile nothing.
    Returns coverage problems (unattributed compiles are the caller's
    diff)."""
    import jax
    import numpy as np

    from ..models import create_model
    from ..ops.masking import make_masks
    from ..serve.engine import InferenceEngine
    from ..train.state import init_variables

    model = create_model("resnet18", num_classes=10, dataset_name="CIFAR10")
    variables = init_variables(
        # graftlint: disable=rng-key-reuse -- fixed key: the audit is a reproducible gate, not a sampler
        model, jax.random.PRNGKey(0), (1, 8, 8, 3)
    )
    params = variables["params"]
    masks = make_masks(params)
    engine = InferenceEngine(
        model,
        params,
        masks,
        variables.get("batch_stats", {}),
        input_shape=(8, 8, 3),
        buckets=(1, 8),  # members of the declared conf bucket sets
    )
    x = np.zeros((3, 8, 8, 3), np.float32)

    before = len(ledger.records)
    with ledger:
        engine.warmup()
        engine.predict(x)  # rides the warmed bucket: zero new compiles
    compiles = len(ledger.records) - before

    problems = []
    if compiles != len(engine.buckets):
        problems.append(
            f"serve: expected exactly {len(engine.buckets)} compiles "
            f"(one per bucket), observed {compiles} — steady-state "
            "predict recompiled"
        )
    kind = engine._plan_signature[0]
    for b in engine.compiled_buckets:
        if not covers(manifest, kind, b):
            problems.append(
                f"serve: compiled (plan={kind!r}, bucket={b}) is outside "
                "the manifest's declared plan kinds x buckets"
            )
    return problems


def _drive_train(ledger: CompileLedger, manifest: dict) -> list:
    """The synthetic train step (shared with --jaxpr-audit) jitted and
    executed once: exactly one compile, named for the step."""
    import jax

    fn, args = default_step_entry("train")
    jitted = jax.jit(fn)
    before = len(ledger.records)
    with ledger:
        out = jitted(*args)
        jax.block_until_ready(out)
    compiles = len(ledger.records) - before
    if compiles != 1:
        return [
            f"train: expected exactly 1 compile for the jitted step, "
            f"observed {compiles}"
        ]
    return []


def _custom_drive(spec: str) -> Callable:
    def drive(ledger: CompileLedger, _manifest: dict) -> list:
        from .drivers import load_builder

        builder, _paths = load_builder(
            spec, error_cls=AuditError, what="--compile-audit target"
        )
        fn = builder()  # setup outside the window, like the built-ins
        if not callable(fn):
            raise AuditError(
                f"--compile-audit: {spec} must return a callable to drive"
            )
        with ledger:
            fn()
        return []

    return drive


# ------------------------------------------------------------------- runner


def run_compile_audit(target: str = "all", print_fn: Callable = print) -> int:
    """Drive, record, attribute. Returns 0 (every compile attributed and
    every (plan, bucket) covered) or 1; raises AuditError for usage
    problems."""
    try:
        import jax  # noqa: F401
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise AuditError(f"--compile-audit needs jax importable: {e}") from e

    target = target or "all"
    if target == "all":
        drivers = [("serve", _drive_serve), ("train", _drive_train)]
    else:
        kind, payload = resolve_runtime_target(
            target,
            {"serve": _drive_serve, "train": _drive_train},
            error_cls=AuditError,
            what="--compile-audit target",
        )
        drivers = [
            (target, payload if kind == "named" else _custom_drive(target))
        ]

    manifest = load_manifest()
    if manifest is None:
        raise AuditError(
            "exec_manifest.json missing — run --exec-manifest emit and "
            "commit it before auditing against it"
        )
    names = executable_names(manifest)
    spans = _manifest_spans(manifest)

    ledger = CompileLedger()
    problems: list = []
    for name, drive in drivers:
        n0 = len(ledger.records)
        problems.extend(drive(ledger, manifest))
        print_fn(
            f"compile-audit: drove {name} "
            f"({len(ledger.records) - n0} compile(s) in the window)"
        )

    unattributed = []
    for rec in ledger.records:
        why = _attribution(rec, names, spans)
        site = rec["site"]
        where = f"{_posix_rel(site[0])}:{site[1]}" if site else "<no repo frame>"
        if why is None:
            unattributed.append(rec)
            print_fn(f"  {rec['name']} from {where} [UNATTRIBUTED]")
        else:
            print_fn(f"  {rec['name']} from {where} [{why}]")

    for p in problems:
        print_fn(f"compile-audit: {p}")
    ok = not unattributed and not problems
    print_fn(
        f"compile-audit: {len(ledger.records)} compile(s), "
        f"{len(unattributed)} unattributed, {len(problems)} coverage "
        f"problem(s) — {'clean' if ok else 'NOT clean'}"
    )
    return 0 if ok else 1
