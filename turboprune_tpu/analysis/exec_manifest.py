"""Executable-set manifests: statically bound the compile surface.

Serving and compact-training live or die by ONE property: the set of XLA
executables a process can ever build is finite and known before it boots
(warmup compiles all of them; steady state never compiles). The shape-flow
rules (shape_rules.py) police the hazards that would break that property;
this module writes the property itself down. It statically enumerates

* **entries** — every lexically-traced function body (regions.py), the
  bodies XLA programs are made from;
* **compile_sites** — every ``jax.jit(...)``-wrapper call, with the
  target function's name resolved through one level of factory
  indirection (``jax.jit(make_eval_step(...))`` resolves to the nested
  ``eval_step`` the factory returns), because the runtime module name of
  a compile is ``jit_<fn.__name__>`` and attribution needs that name;
* **bucket_sets** — every declared batch-bucket set: literal int tuples
  assigned to bucket-named symbols in the package and ``batch_buckets``
  (or any bucket-named list) in ``conf/**/*.yaml``;
* **plan_kinds** — every ``PLAN_SIGNATURE_KIND = "..."`` declaration
  (sparse/compact.py, sparse/nm_execute.py, serve/engine.py): the plan
  vocabulary AOT cache keys may carry.

The product (entries+sites) x (bucket union) x (plan kinds) is the entire
legal compile surface. It is checked in as ``exec_manifest.json`` next to
this file; ``graftlint --exec-manifest diff`` fails when code grows a jit
entry / bucket / plan kind the manifest doesn't know (re-emit to accept),
and ``--compile-audit`` (compile_audit.py) holds a real run to it.

Pure stdlib at import time, like the rest of the package; the yaml parse
degrades to a regex scan when PyYAML is unavailable.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Optional

from .core import _collect_project_files, is_test_file
from .project import ProjectIndex
from .regions import build_jit_regions, dotted_name, is_jit_wrapper, unwrap_partial

__all__ = [
    "MANIFEST_PATH",
    "build_manifest",
    "covers",
    "executable_names",
    "load_manifest",
    "run_exec_manifest",
]

MANIFEST_PATH = Path(__file__).resolve().parent / "exec_manifest.json"
MANIFEST_VERSION = 1


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _rel(path) -> str:
    p = Path(path).resolve()
    try:
        return p.relative_to(_repo_root()).as_posix()
    except ValueError:
        return p.as_posix()


def _default_paths() -> list:
    pkg = Path(__file__).resolve().parents[1]
    paths = [pkg]
    conf = pkg.parent / "conf"
    if conf.is_dir():
        paths.append(conf)
    return paths


# ----------------------------------------------------------- python scans


def _int_seq(node: ast.AST) -> Optional[list]:
    """A literal tuple/list of >= 1 ints -> the ints; else None."""
    if not isinstance(node, (ast.Tuple, ast.List)) or not node.elts:
        return None
    out = []
    for e in node.elts:
        if not (
            isinstance(e, ast.Constant)
            and isinstance(e.value, int)
            and not isinstance(e.value, bool)
        ):
            return None
        out.append(e.value)
    return out


def _bucket_named(name: Optional[str]) -> bool:
    return bool(name) and "bucket" in name.lower()


def _py_bucket_sets(mi) -> dict:
    """``{"<file>:<symbol>": [ints]}`` for bucket declarations in one
    module: literal int-sequence assigns to bucket-named targets (the
    sequence may sit behind a default_factory lambda, as in the serve
    config schema)."""
    out: dict = {}
    rel = _rel(mi.path)
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        names = [
            t.id
            for t in targets
            if isinstance(t, ast.Name) and _bucket_named(t.id)
        ]
        if not names:
            continue
        seq = _int_seq(value)
        if seq is None:
            for sub in ast.walk(value):
                seq = _int_seq(sub)
                if seq is not None:
                    break
        if seq is not None:
            for name in names:
                out[f"{rel}:{name}"] = seq
    return out


def _site_target(arg: ast.AST, mi, index, graph, scope) -> str:
    """The best static name for what a jit-wrapper call compiles — chosen
    to line up with the runtime module name ``jit_<fn.__name__>``."""
    node = unwrap_partial(arg)
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr  # bound method: __name__ is the attr tail
    if isinstance(node, ast.Call):
        # factory call: jit(make_eval_step(...)) compiles the nested def
        # the factory returns, and THAT def's name is the runtime name
        callee = index.resolve_call(mi, node.func, scope)
        if callee is not None:
            nested = graph.returns_nested(callee)
            if nested is not None:
                return nested.name
        return dotted_name(node.func) or "?"
    return "?"


def _scan_python(py_files) -> tuple:
    """(entries, compile_sites, bucket_sets, plan_kinds) over the package.

    Test files are excluded: the manifest bounds what SHIPPING code can
    compile; tests construct throwaway jits on purpose. The analysis
    package itself is excluded too — its audit drivers jit on purpose,
    and the runtime half (compile_audit._repo_site) symmetrically skips
    analysis/ frames when attributing."""
    from .rules import _own_statements, _walk_no_nested_defs

    analysis_dir = Path(__file__).resolve().parent
    contexts = []
    for f in py_files:
        if is_test_file(f):
            continue
        if Path(f).resolve().parent == analysis_dir:
            continue
        try:
            tree = ast.parse(Path(f).read_text(encoding="utf-8"))
        except SyntaxError:
            continue  # the lint gate owns parse errors
        contexts.append((str(f), tree))

    class _Ctx:  # the minimal shape ProjectIndex.build consumes
        def __init__(self, path, tree):
            self.path, self.tree = path, tree

    index = ProjectIndex.build(_Ctx(p, t) for p, t in contexts)
    from .callgraph import CallGraph

    graph = CallGraph(index)

    entries: list = []
    sites: list = []
    bucket_sets: dict = {}
    plan_kinds: dict = {}

    for path, tree in contexts:
        rel = _rel(path)
        for r in build_jit_regions(tree):
            entries.append(
                {
                    "name": getattr(r.node, "name", "<lambda>"),
                    "file": rel,
                    "line": r.start,
                    "end": r.end,
                    "reason": r.reason,
                }
            )
        mi = index.module_for_path(path)
        if mi is None:
            continue
        bucket_sets.update(_py_bucket_sets(mi))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "PLAN_SIGNATURE_KIND"
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                plan_kinds[node.value.value] = f"{rel}:{node.lineno}"
        scopes = [(None, mi.tree.body)]
        scopes.extend(
            (fi, fi.node.body)
            for fi in index.functions.values()
            if fi.path == mi.path
        )
        for scope, body in scopes:
            for node in _walk_no_nested_defs(_own_statements(body)):
                if (
                    isinstance(node, ast.Call)
                    and is_jit_wrapper(node.func)
                    and node.args
                ):
                    sites.append(
                        {
                            "target": _site_target(
                                node.args[0], mi, index, graph, scope
                            ),
                            "file": rel,
                            "line": node.lineno,
                        }
                    )
    return entries, sites, bucket_sets, plan_kinds


# ------------------------------------------------------------- yaml scans

_YAML_BUCKET_RE = re.compile(
    r"^(\w*bucket\w*)\s*:\s*\[([0-9,\s]+)\]", re.MULTILINE
)


def _walk_yaml(data, prefix, out) -> None:
    if isinstance(data, dict):
        for k, v in data.items():
            key = str(k)
            if (
                _bucket_named(key)
                and isinstance(v, list)
                and v
                and all(isinstance(i, int) and not isinstance(i, bool) for i in v)
            ):
                out[f"{prefix}:{key}"] = list(v)
            else:
                _walk_yaml(v, prefix, out)
    elif isinstance(data, list):
        for v in data:
            _walk_yaml(v, prefix, out)


def _yaml_bucket_sets(yaml_files) -> dict:
    out: dict = {}
    for f, _root in yaml_files:
        rel = _rel(f)
        try:
            text = Path(f).read_text(encoding="utf-8")
        except OSError:
            continue
        try:
            import yaml

            _walk_yaml(yaml.safe_load(text), rel, out)
        except Exception:  # graftlint: disable=broad-except -- no PyYAML / unparsable yaml degrades to the regex scan; conf lint owns yaml errors
            for m in _YAML_BUCKET_RE.finditer(text):
                vals = [int(x) for x in m.group(2).split(",") if x.strip()]
                if vals:
                    out[f"{rel}:{m.group(1)}"] = vals
    return out


# ------------------------------------------------------------ the manifest


def build_manifest(paths=None) -> dict:
    """The static compile-surface manifest over ``paths`` (default: the
    package + conf/). Deterministic: everything sorted, paths repo-relative
    posix — same tree, same JSON, so ``diff`` is a pure content check."""
    py_files, yaml_files = _collect_project_files(paths or _default_paths())
    entries, sites, bucket_sets, plan_kinds = _scan_python(py_files)
    bucket_sets.update(_yaml_bucket_sets(yaml_files))
    entries.sort(key=lambda e: (e["file"], e["line"], e["name"]))
    sites.sort(key=lambda s: (s["file"], s["line"], s["target"]))
    buckets = sorted({b for vals in bucket_sets.values() for b in vals})
    return {
        "version": MANIFEST_VERSION,
        "entries": entries,
        "compile_sites": sites,
        "bucket_sets": {k: bucket_sets[k] for k in sorted(bucket_sets)},
        "buckets": buckets,
        "plan_kinds": {k: plan_kinds[k] for k in sorted(plan_kinds)},
    }


def load_manifest(path=None) -> Optional[dict]:
    p = Path(path) if path else MANIFEST_PATH
    if not p.is_file():
        return None
    return json.loads(p.read_text(encoding="utf-8"))


def executable_names(manifest: dict) -> set:
    """Every function name the manifest says may become an XLA module:
    runtime compiles are named ``jit_<fn.__name__>``, so attribution is a
    membership test against this set."""
    return {e["name"] for e in manifest.get("entries", ())} | {
        s["target"] for s in manifest.get("compile_sites", ())
    }


def covers(manifest: dict, plan_kind: str, bucket: int) -> bool:
    """Is (plan kind, bucket) inside the statically-declared surface?"""
    return plan_kind in manifest.get("plan_kinds", {}) and int(bucket) in set(
        manifest.get("buckets", ())
    )


def _dumps(manifest: dict) -> str:
    return json.dumps(manifest, indent=1, sort_keys=True) + "\n"


def _diff_lists(name, old, new, print_fn) -> int:
    o = {json.dumps(x, sort_keys=True) for x in old}
    n = {json.dumps(x, sort_keys=True) for x in new}
    bad = 0
    for item in sorted(n - o):
        print_fn(f"  + {name}: {item}")
        bad += 1
    for item in sorted(o - n):
        print_fn(f"  - {name}: {item}")
        bad += 1
    return bad


def run_exec_manifest(mode: str = "diff", paths=None, print_fn=print) -> int:
    """CLI driver: ``emit`` writes the manifest, ``print`` dumps it,
    ``diff`` (the check.sh stage) rebuilds and compares to the checked-in
    file — exit 1 on drift, with the drift itemized."""
    if mode not in ("emit", "diff", "print"):
        raise ValueError(
            f"unknown --exec-manifest mode {mode!r}; expected emit, diff "
            "or print"
        )
    manifest = build_manifest(paths)
    if mode == "print":
        print_fn(_dumps(manifest).rstrip("\n"))
        return 0
    if mode == "emit":
        MANIFEST_PATH.write_text(_dumps(manifest), encoding="utf-8")
        print_fn(
            f"exec-manifest: wrote {_rel(MANIFEST_PATH)} "
            f"({len(manifest['entries'])} entries, "
            f"{len(manifest['compile_sites'])} compile sites, "
            f"{len(manifest['buckets'])} buckets, "
            f"{len(manifest['plan_kinds'])} plan kinds)"
        )
        return 0
    checked_in = load_manifest()
    if checked_in is None:
        print_fn(
            f"exec-manifest: {_rel(MANIFEST_PATH)} missing — run "
            "--exec-manifest emit and commit it"
        )
        return 1
    bad = 0
    for key in ("entries", "compile_sites"):
        bad += _diff_lists(key, checked_in.get(key, []), manifest[key], print_fn)
    for key in ("bucket_sets", "plan_kinds"):
        old, new = checked_in.get(key, {}), manifest[key]
        for k in sorted(set(old) | set(new)):
            if old.get(k) != new.get(k):
                print_fn(f"  ~ {key}[{k}]: {old.get(k)} -> {new.get(k)}")
                bad += 1
    if checked_in.get("buckets") != manifest["buckets"]:
        print_fn(
            f"  ~ buckets: {checked_in.get('buckets')} -> "
            f"{manifest['buckets']}"
        )
        bad += 1
    if bad:
        print_fn(
            f"exec-manifest: {bad} difference(s) vs {_rel(MANIFEST_PATH)} — "
            "the compile surface changed; review and re-emit"
        )
        return 1
    print_fn(
        f"exec-manifest: clean ({len(manifest['entries'])} entries, "
        f"{len(manifest['compile_sites'])} compile sites, "
        f"buckets {manifest['buckets']}, "
        f"plan kinds {sorted(manifest['plan_kinds'])})"
    )
    return 0
