"""Lexical jit/trace region detection.

"Inside jit" is a dynamic property, but in this codebase (and most JAX
code) it is almost always visible lexically: a function is traced because
it is decorated with ``jax.jit``/``@partial(jax.jit, ...)``, passed to a
transform (``jax.jit(f)``, ``shard_map(f, ...)``), or used as the body of a
control-flow primitive (``lax.scan``/``cond``/``while_loop``). This module
finds those function bodies and records which parameters are traced
(``static_argnames`` are Python values, so ``float(static_flag)`` is fine
while ``float(traced_x)`` is a device sync).

Known blind spot, by design: a plain function that is only jitted at a
distant call site (e.g. train/steps.py step fns jitted inside
parallel/mesh.py factories) is not marked — interprocedural analysis is
out of scope. The rules built on this index therefore never claim
completeness; they claim zero false negatives on the LEXICAL patterns,
which is what the positive/negative fixture tests in
tests/test_analysis.py pin down.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

__all__ = [
    "JitRegion",
    "build_jit_regions",
    "donation_spec",
    "dotted_name",
    "is_jit_wrapper",
    "is_tracing_call",
    "partial_bindings",
    "unwrap_partial",
]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` -> "jax.lax.scan"; None for non-name expressions."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


# Wrappers that COMPILE the function they receive.
_JIT_TAILS = {"jit", "pjit"}
# Transforms/primitives that TRACE a function argument. Bare names are
# accepted only for the ones this repo imports unqualified; the generic
# short words (scan, map, cond, ...) require a lax/jax prefix so we don't
# flag builtins or unrelated helpers.
_TRACE_BARE = {
    "jit",
    "pjit",
    "shard_map",
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "remat",
}
_TRACE_TRANSFORM_TAILS = {
    "jit",
    "pjit",
    "shard_map",
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "remat",
    "checkpoint",
}
# Short generic words (scan, map, cond...) are tracing ONLY under lax —
# jax.tree.map / builtins.map must not match.
_TRACE_LAX_TAILS = {
    "scan",
    "cond",
    "while_loop",
    "fori_loop",
    "map",
    "switch",
    "associative_scan",
}
_JAXY_ROOTS = {"jax", "lax", "nn"}


def is_jit_wrapper(func: ast.AST) -> bool:
    """True for ``jax.jit`` / ``jit`` / ``pjit`` style callables."""
    name = dotted_name(func)
    if not name:
        return False
    parts = name.split(".")
    return parts[-1] in _JIT_TAILS and (
        len(parts) == 1 or parts[0] in _JAXY_ROOTS
    )


def is_tracing_call(func: ast.AST) -> bool:
    name = dotted_name(func)
    if not name:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        return parts[0] in _TRACE_BARE
    if parts[-1] in _TRACE_LAX_TAILS:
        return parts[-2] == "lax"
    return parts[-1] in _TRACE_TRANSFORM_TAILS and parts[0] in _JAXY_ROOTS


def _is_partial(func: ast.AST) -> bool:
    name = dotted_name(func)
    return name in ("partial", "functools.partial")


def literal_str_seq(node: ast.AST) -> Optional[list]:
    """``"x"`` or ``("x", "y")``/``["x"]`` -> list of strings; else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def param_names(fn: ast.AST) -> list:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    return names


@dataclasses.dataclass
class JitRegion:
    """One traced function body."""

    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    start: int
    end: int
    reason: str  # human-readable: how this body ends up traced
    traced_params: frozenset  # param names that are traced values

    def walk(self):
        return ast.walk(self.node)


def _static_names_from_call(call: ast.Call) -> list:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            return literal_str_seq(kw.value) or []
    return []


def _region_for_def(
    fn: ast.AST, reason: str, static: list = ()
) -> JitRegion:
    traced = [p for p in param_names(fn) if p not in set(static)]
    # `self` is never a traced array in this codebase's method style.
    traced = [p for p in traced if p != "self"]
    return JitRegion(
        node=fn,
        start=fn.lineno,
        end=fn.end_lineno or fn.lineno,
        reason=reason,
        traced_params=frozenset(traced),
    )


def unwrap_partial(node: ast.AST) -> ast.AST:
    """partial(f, ...) -> f (one level is all the repo uses)."""
    return partial_bindings(node)[0]


def partial_bindings(node: ast.AST) -> tuple:
    """``partial(f, a, b, kw=c)`` -> ``(f, 2, frozenset({"kw"}))``; anything
    that is not a partial call -> ``(node, 0, frozenset())``.

    The bound count matters for scan bodies: ``lax.scan(partial(body,
    model), init, xs)`` binds ``body``'s LEADING params as Python values at
    trace time — only the params after them are traced (carry first)."""
    if (
        isinstance(node, ast.Call)
        and _is_partial(node.func)
        and node.args
    ):
        kw = frozenset(k.arg for k in node.keywords if k.arg)
        return node.args[0], len(node.args) - 1, kw
    return node, 0, frozenset()


def donation_spec(call: ast.Call):
    """``(argnums, argnames)`` from a jit-wrapper call carrying donation
    keywords, or None. Shared by the per-file donated-arg-reuse rule and
    the callgraph's donating-factory summary."""
    if not isinstance(call, ast.Call) or not is_jit_wrapper(call.func):
        return None
    nums, names = [], []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.append(e.value)
        elif kw.arg == "donate_argnames":
            names.extend(literal_str_seq(kw.value) or [])
    return (tuple(nums), tuple(names)) if (nums or names) else None


def build_jit_regions(tree: ast.Module) -> list:
    """All lexically-traced function bodies in a module."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    regions: dict[tuple, JitRegion] = {}

    def add(region: JitRegion) -> None:
        regions.setdefault((region.start, region.end), region)

    def add_callable(node: ast.AST, reason: str, static: list) -> None:
        node, n_bound, bound_kw = partial_bindings(node)
        if isinstance(node, ast.Lambda):
            add(
                JitRegion(
                    node=node,
                    start=node.lineno,
                    end=node.end_lineno or node.lineno,
                    reason=reason,
                    traced_params=frozenset(
                        p for p in param_names(node)[n_bound:]
                        if p not in set(static) | bound_kw
                    ),
                )
            )
        elif isinstance(node, ast.Name) and node.id in defs:
            fn = defs[node.id]
            # partial-bound leading positionals (and bound keywords) are
            # Python values at trace time, not traced operands
            bound = set(param_names(fn)[:n_bound]) | set(bound_kw)
            add(_region_for_def(fn, reason, list(static) + sorted(bound)))

    for node in ast.walk(tree):
        # -- decorated defs: @jax.jit / @partial(jax.jit, static_argnames=..)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_wrapper(dec):
                    add(_region_for_def(node, f"@{dotted_name(dec)}"))
                elif isinstance(dec, ast.Call):
                    if is_jit_wrapper(dec.func):
                        add(
                            _region_for_def(
                                node,
                                f"@{dotted_name(dec.func)}(...)",
                                _static_names_from_call(dec),
                            )
                        )
                    elif _is_partial(dec.func) and dec.args and is_jit_wrapper(
                        dec.args[0]
                    ):
                        add(
                            _region_for_def(
                                node,
                                f"@partial({dotted_name(dec.args[0])}, ...)",
                                _static_names_from_call(dec),
                            )
                        )
        # -- function arguments to jit/shard_map/lax control flow
        elif isinstance(node, ast.Call) and is_tracing_call(node.func):
            static = _static_names_from_call(node)
            reason = f"passed to {dotted_name(node.func)}"
            for arg in node.args:
                add_callable(arg, reason, static)

    return sorted(regions.values(), key=lambda r: (r.start, r.end))
