"""``--jaxpr-audit``: ground the static dtype rules in the real jaxpr.

The dtype-flow rules (dtype_rules.py) are an at-rest approximation of
JAX's promotion table; the compiler's own record of every promotion is the
``convert_element_type`` equations in the jaxpr. This mode traces the real
train/eval step under a declared dtype policy and diffs the two views:

* every reduced->f32/f64 ``convert_element_type`` in the traced jaxpr is
  located via its source frame and matched against (a) dtype-rule waivers,
  (b) static dtype findings, (c) an explicit cast on the source line
  (``astype``/``convert_element_type``/``asarray`` — a visible decision);
* an upcast none of those explain is a static-analysis blind spot and
  fails the audit, as does any unwaived static dtype finding over the
  audited files (static and dynamic must BOTH be clean).

Under the default fp32 policy nothing is reduced, so the synthetic-task
step must audit to zero upcasts — that's the regression gate. Under
``--dtype-policy bf16`` the audit is the acceptance harness for ROADMAP
item 6's mixed-precision PR: it shows exactly which promotions the bf16
step would reintroduce, before any of it lands.

jax imports live inside functions: the analysis package stays importable
with no accelerator stack, and only this mode pays for the tracer.

Entry points: ``train`` / ``eval`` build the synthetic-task step (tiny
resnet18, CIFAR-shaped inputs); ``path/to/file.py:fn`` or
``pkg.module:fn`` calls ``fn()`` which must return ``(step_fn, args)`` —
the audit traces ``step_fn(*args)`` and statically analyzes the file that
defines it.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Iterator, Optional

from .core import analyze_paths
from .drivers import default_step_entry, resolve_runtime_target

__all__ = ["AuditError", "DTYPE_RULE_IDS", "run_audit"]

DTYPE_RULE_IDS = (
    "silent-upcast",
    "weak-type-promotion",
    "scan-carry-dtype-drift",
    "missing-preferred-element-type",
)

_REDUCED_NAMES = {"bfloat16", "float16"}
_WIDE_NAMES = {"float32", "float64"}
_EXPLICIT_MARKERS = ("astype", "convert_element_type", "asarray")
_NEAR_LINES = 2  # inference anchors vs trace frames can differ on multiline exprs


class AuditError(RuntimeError):
    """Usage/environment error (CLI maps it to exit code 2)."""


# ------------------------------------------------------------- entries


def _load_entry(entry: str, policy: str):
    """``(step_fn, args, static_paths)`` for an entry spec. Named entries
    and builder specs resolve through the shared registry (drivers.py), so
    the three runtime modes accept identical target grammar."""
    pkg = Path(__file__).resolve().parents[1]
    kind, payload = resolve_runtime_target(
        entry,
        {"train": "train", "eval": "eval"},
        error_cls=AuditError,
        what="--jaxpr-audit entry",
    )
    if kind == "named":
        fn, args = default_step_entry(payload, policy)
        return fn, args, [pkg / "train", pkg / "ops"]
    builder, static_paths = payload
    fn, args = builder()
    return fn, args, static_paths


# --------------------------------------------------------- jaxpr walking


def _sub_jaxprs(v) -> Iterator:
    items = v if isinstance(v, (tuple, list)) else (v,)
    for x in items:
        inner = getattr(x, "jaxpr", None)  # ClosedJaxpr -> Jaxpr
        if inner is not None and hasattr(inner, "eqns"):
            yield inner
        elif hasattr(x, "eqns"):
            yield x


def _iter_eqns(jaxpr) -> Iterator:
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _source_site(eqn) -> Optional[tuple]:
    """(file, line) of the first user frame behind an equation, if jax
    exposes it (source_info_util is jax-internal; degrade to None)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None
        return str(frame.file_name), int(frame.start_line)
    # graftlint: disable=broad-except -- jax-internal API drift degrades to "no source frame", which the diff reports
    except Exception:
        return None


def _dtype_name(d) -> str:
    try:
        import numpy as np

        return str(np.dtype(d))
    # graftlint: disable=broad-except -- extended dtypes (key<fry>) reject np.dtype(); the raw repr is fine for the report
    except Exception:
        return str(d)


def _collect_upcasts(closed_jaxpr) -> tuple:
    """``(total_eqns, [(file|None, line|None, old, new), ...])``."""
    total = 0
    upcasts = []
    for eqn in _iter_eqns(closed_jaxpr.jaxpr):
        total += 1
        if eqn.primitive.name != "convert_element_type":
            continue
        new = _dtype_name(eqn.params.get("new_dtype"))
        old = _dtype_name(getattr(eqn.invars[0].aval, "dtype", ""))
        if old in _REDUCED_NAMES and new in _WIDE_NAMES:
            site = _source_site(eqn)
            file, line = site if site else (None, None)
            upcasts.append((file, line, old, new))
    return total, upcasts


# --------------------------------------------------------------- the diff


def _same_file(a: Optional[str], b: str) -> bool:
    if a is None:
        return False
    try:
        return Path(a).resolve() == Path(b).resolve()
    except OSError:
        return False


def _explain(file, line, old, new, result) -> tuple:
    """``(status, detail)``: how the static layer accounts for one upcast.
    status: 'waiver' | 'finding' | 'explicit-cast' | 'unexplained'."""
    if file is None:
        return "unexplained", "no source frame"
    for w in result.waivers:
        if (
            w.rules & set(DTYPE_RULE_IDS)
            and _same_file(file, w.file)
            and abs(w.applies_to - line) <= _NEAR_LINES
        ):
            return "waiver", w.reason or "no reason given"
    for f in result.findings:
        if (
            f.rule in DTYPE_RULE_IDS
            and _same_file(file, f.file)
            and abs(f.line - line) <= _NEAR_LINES
        ):
            if f.waived:
                return "waiver", f.waiver_reason or "no reason given"
            return "finding", f"{f.rule} at {f.file}:{f.line}"
    try:
        text = Path(file).read_text(encoding="utf-8").splitlines()[line - 1]
    except (OSError, IndexError):
        text = ""
    if any(m in text for m in _EXPLICIT_MARKERS):
        return "explicit-cast", text.strip()
    return "unexplained", text.strip() or "??"


def run_audit(
    entry: str = "train",
    policy: str = "fp32",
    print_fn: Callable = print,
) -> int:
    """Trace, collect reduced->wide converts, diff against the static
    layer. Returns 0 (clean) or 1 (unexplained upcasts and/or unwaived
    static dtype findings). Raises AuditError for usage problems."""
    try:
        import jax
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise AuditError(f"--jaxpr-audit needs jax importable: {e}") from e

    fn, args, static_paths = _load_entry(entry, policy)
    closed = jax.make_jaxpr(fn)(*args)
    total, upcasts = _collect_upcasts(closed)

    result = analyze_paths(static_paths, select=list(DTYPE_RULE_IDS))
    unwaived_static = [f for f in result.findings if not f.waived]

    print_fn(f"jaxpr-audit: entry={entry} policy={policy}")
    print_fn(
        f"  traced {total} eqn(s); {len(upcasts)} reduced->wide "
        "convert_element_type op(s)"
    )
    bad = 0
    for file, line, old, new in upcasts:
        status, detail = _explain(file, line, old, new, result)
        where = f"{file}:{line}" if file else "<no source frame>"
        print_fn(f"  {where}: {old} -> {new} [{status}] {detail}")
        if status in ("finding", "unexplained"):
            bad += 1
    print_fn(
        f"  static dtype findings over {', '.join(str(p) for p in static_paths)}: "
        f"{len(unwaived_static)} unwaived, "
        f"{len(result.findings) - len(unwaived_static)} waived"
    )
    for f in unwaived_static:
        print_fn(f"  static: {f.file}:{f.line}: {f.rule}: {f.message}")
    ok = bad == 0 and not unwaived_static
    print_fn(f"jaxpr-audit: {'clean' if ok else 'NOT clean'}")
    return 0 if ok else 1
