"""graftsan — runtime concurrency sanitizer, the dynamic mirror of the
static thread rules (concurrency_rules.py), exactly as jaxpr_audit.py is
the dynamic mirror of the dtype rules.

The static layer proves properties of a LEXICAL thread model: spawn sites
it can resolve, locks it can name, accesses it can see. This module checks
the same two properties against what a real run actually does:

* **lock order** — ``threading.Lock``/``RLock``/``Condition`` factories are
  patched for the duration of a run; every lock CREATED BY PACKAGE CODE
  (creation-site frame filter — stdlib internals like ``queue.Queue``'s
  conditions stay unwrapped) is wrapped so each acquire records a
  held-before edge. A cycle in the observed acquisition-order graph (or a
  re-acquire of a held non-reentrant Lock) is a deadlock witness: exit 1,
  no exceptions.
* **shared writes** — ``watch(cls)`` patches ``cls.__setattr__`` to record
  (instance, attribute, thread, lockset held). An attribute rebound by two
  or more threads on the same instance with no common lock is an observed
  race. Each observed race is then diffed against the static
  ``unsynchronized-shared-mutation`` findings (waived findings count — a
  waiver is still an explanation): an observed race the static layer never
  claimed is a BLIND SPOT in the lexical model and fails the run, the same
  contract as an unexplained convert_element_type in the jaxpr audit.

Two built-in drivers put the package's real concurrent subsystems under
load: ``pipeline`` (PrefetchEngine: pool decoders + transfer thread +
concurrent stats readers + racing closes) and ``fleet`` (a 2-model
FleetEngine with ``max_resident_models=1`` so page-in/evict churns under
concurrent submitters; engines are faked so no checkpoint or compiler is
needed). ``file.py:builder`` drives a custom callable. Exit codes follow
the CLI contract: 0 clean, 1 cycle or unexplained race, 2 usage error.

First-write exemption: the first rebind of each (instance, attribute) is
init-time by construction (``__init__`` runs before the object is shared)
and is not counted.
"""

from __future__ import annotations

import sys
import threading
import time
from pathlib import Path

from .drivers import load_builder, resolve_runtime_target

__all__ = ["Graftsan", "SanitizeError", "run_sanitize"]

_PKG_ROOT = str(Path(__file__).resolve().parents[1])
_SELF = str(Path(__file__).resolve())

_KINDS = ("Lock", "RLock", "Condition")


class SanitizeError(RuntimeError):
    """Usage/environment error (unknown target, missing builder): exit 2."""


class _LockWrapper:
    """Records acquire/release against the owning Graftsan; everything else
    delegates to the real primitive (so e.g. ``Condition(lock=wrapper)``
    still finds ``locked()`` and misses ``_release_save`` exactly like the
    real Lock would)."""

    def __init__(self, san, real, kind, site, uid):
        self._san = san
        self._real = real
        self._kind = kind  # "lock" | "rlock" | "condition"
        self._site = site
        self._uid = uid

    def acquire(self, blocking=True, timeout=-1):
        self._san._pre_acquire(self)
        got = self._real.acquire(blocking, timeout)
        if got:
            self._san._did_acquire(self)
        return got

    def release(self):
        self._real.release()
        self._san._did_release(self)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def __getattr__(self, name):
        return getattr(self._real, name)

    def __repr__(self):
        return f"<graftsan {self._kind} #{self._uid} from {self._site}>"


class _CondWrapper(_LockWrapper):
    """Condition: ``wait`` releases the lock for its duration, so the held
    stack must drop it on entry and restore it on return (the restore
    records no order edge — the reacquire is protocol, not policy)."""

    def wait(self, timeout=None):
        self._san._did_release(self)
        try:
            return self._real.wait(timeout)
        finally:
            self._san._did_acquire(self)

    def wait_for(self, predicate, timeout=None):
        end = None if timeout is None else time.monotonic() + timeout
        result = predicate()
        while not result:
            left = None if end is None else end - time.monotonic()
            if left is not None and left <= 0:
                break
            self.wait(left)
            result = predicate()
        return result


class Graftsan:
    """Context manager: patch the lock factories, observe, unpatch.

    ``include`` limits wrapping to locks whose creation site (the frame
    calling the factory) lives under one of the given path prefixes;
    default is the turboprune_tpu package."""

    def __init__(self, include=None):
        self._include = tuple(str(p) for p in include) if include else (_PKG_ROOT,)
        # Real primitives captured NOW, before any patching, so the
        # sanitizer's own bookkeeping never runs through a wrapper.
        self._mu = threading.Lock()
        self._real_factories: dict = {}
        self._held: dict = {}  # thread id -> [wrapper] (acquisition order)
        self._sites: dict = {}  # uid -> (site, kind)
        self._edges: dict = {}  # (uid_a, uid_b) -> witness dict
        self._writes: dict = {}  # (obj id, cls name, attr) -> [(tid, held)]
        self._first: set = set()  # (obj id, attr): init-write exemption
        # Strong refs to every watched instance: id() keys above are only
        # meaningful while the object is alive — letting an evicted object
        # die would let a NEW instance reuse its id and inherit its
        # first-write exemptions (its unguarded __init__ writes would then
        # read as races).
        self._keepalive: dict = {}
        self._watched: list = []  # (cls, original __setattr__ or None)
        self._uid = 0
        self.lock_count = 0
        self._active = False

    # ------------------------------------------------------------ patching
    def __enter__(self) -> "Graftsan":
        for kind in _KINDS:
            self._real_factories[kind] = getattr(threading, kind)
            setattr(threading, kind, self._factory(kind))
        self._active = True
        return self

    def __exit__(self, *exc) -> None:
        self._active = False
        for kind, real in self._real_factories.items():
            setattr(threading, kind, real)
        for cls, orig in reversed(self._watched):
            if orig is None:
                try:
                    delattr(cls, "__setattr__")
                except AttributeError:
                    pass
            else:
                cls.__setattr__ = orig
        self._watched.clear()

    def _factory(self, kind):
        real_ctor = self._real_factories[kind]
        san = self

        def make(*args, **kwargs):
            real = real_ctor(*args, **kwargs)
            frame = sys._getframe(1)
            fname = frame.f_code.co_filename
            if fname == _SELF or not fname.startswith(san._include):
                return real
            with san._mu:
                san._uid += 1
                san.lock_count += 1
                uid = san._uid
                site = f"{fname}:{frame.f_lineno}"
                san._sites[uid] = (site, kind.lower())
            cls = _CondWrapper if kind == "Condition" else _LockWrapper
            return cls(san, real, kind.lower(), site, uid)

        return make

    # ----------------------------------------------------------- lock events
    def _pre_acquire(self, w) -> None:
        tid = threading.get_ident()
        with self._mu:
            held = self._held.get(tid, [])
            if any(h is w for h in held):
                if w._kind == "lock":
                    # Non-reentrant Lock re-acquired by its holder: this
                    # thread is now deadlocked for real — record the
                    # self-edge so cycles() reports it even though the run
                    # will need its timeout to notice.
                    self._edges.setdefault(
                        (w._uid, w._uid),
                        {
                            "from": w._site,
                            "to": w._site,
                            "thread": threading.current_thread().name,
                        },
                    )
                return  # RLock/Condition re-entry is legal, no edge
            for h in held:
                self._edges.setdefault(
                    (h._uid, w._uid),
                    {
                        "from": h._site,
                        "to": w._site,
                        "thread": threading.current_thread().name,
                    },
                )

    def _did_acquire(self, w) -> None:
        with self._mu:
            self._held.setdefault(threading.get_ident(), []).append(w)

    def _did_release(self, w) -> None:
        with self._mu:
            held = self._held.get(threading.get_ident(), [])
            for i in range(len(held) - 1, -1, -1):
                if held[i] is w:
                    del held[i]
                    break

    # --------------------------------------------------------- write events
    def watch(self, cls) -> None:
        """Record every attribute rebind on instances of ``cls`` with the
        writing thread and its lockset."""
        if any(c is cls for c, _ in self._watched):
            return
        orig_in_dict = "__setattr__" in vars(cls)
        orig = cls.__setattr__
        san = self

        def _setattr(obj, name, value, _orig=orig):
            _orig(obj, name, value)
            san._record_write(obj, name)

        cls.__setattr__ = _setattr
        self._watched.append((cls, orig if orig_in_dict else None))

    def _record_write(self, obj, attr) -> None:
        if not self._active:
            return
        tid = threading.get_ident()
        with self._mu:
            self._keepalive[id(obj)] = obj
            first_key = (id(obj), attr)
            if first_key not in self._first:
                self._first.add(first_key)
                return
            held = frozenset(w._uid for w in self._held.get(tid, ()))
            key = (id(obj), type(obj).__name__, attr)
            self._writes.setdefault(key, []).append((tid, held))

    # ------------------------------------------------------------- verdicts
    def order_edges(self) -> list:
        with self._mu:
            return [
                {"from": self._sites[a][0], "to": self._sites[b][0], **w}
                for (a, b), w in sorted(self._edges.items())
            ]

    def cycles(self) -> list:
        """Cycles in the observed acquisition-order graph, each a dict with
        the participating creation sites and the witnessing edges."""
        with self._mu:
            edges = dict(self._edges)
            sites = dict(self._sites)
        adj: dict = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        out = []
        for scc in _sccs(adj):
            if len(scc) > 1 or (scc[0], scc[0]) in edges:
                members = set(scc)
                witness = [
                    f"{sites[a][0]} -> {sites[b][0]} [{w['thread']}]"
                    for (a, b), w in sorted(edges.items())
                    if a in members and b in members
                ]
                out.append(
                    {
                        "locks": sorted(sites[u][0] for u in scc),
                        "edges": witness,
                    }
                )
        return sorted(out, key=lambda c: c["locks"])

    def races(self) -> list:
        """Attributes rebound by >= 2 threads on one instance with no
        common lock, aggregated to (class, attr) for the static diff."""
        seen: dict = {}
        with self._mu:
            items = sorted(self._writes.items(), key=lambda kv: kv[0][1:])
        for (_oid, cls, attr), ws in items:
            threads = {t for t, _ in ws}
            if len(threads) < 2:
                continue
            common = frozenset.intersection(*(h for _, h in ws))
            if common:
                continue
            row = seen.setdefault(
                (cls, attr),
                {"cls": cls, "attr": attr, "writes": 0, "threads": 0},
            )
            row["writes"] += len(ws)
            row["threads"] = max(row["threads"], len(threads))
        return [seen[k] for k in sorted(seen)]


def _sccs(adj: dict) -> list:
    """Iterative Tarjan over the uid graph; returns every SCC."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]
    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    u = stack.pop()
                    on_stack.discard(u)
                    scc.append(u)
                    if u == node:
                        break
                sccs.append(sorted(scc))
    return sccs


# ------------------------------------------------------------------ drivers


def _drive_pipeline(san: Graftsan) -> None:
    """PrefetchEngine under the exact load shape its races would need:
    pool decoders + the transfer thread + concurrent stats readers +
    three racing close() calls at the end (the close-idempotence race)."""
    import numpy as np

    from ..data.pipeline import PrefetchEngine

    san.watch(PrefetchEngine)

    total = 64

    def mk(i):
        def task():
            time.sleep(0.0002)
            return np.full((8,), i, np.int64)

        return task

    eng = PrefetchEngine(
        (mk(i) for i in range(total)),
        lambda batches: list(batches),
        depth=4,
        workers=4,
        group=2,
        name="graftsan",
    )
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            eng.stats()
            time.sleep(0.0002)

    readers = [threading.Thread(target=poll, daemon=True) for _ in range(2)]
    for t in readers:
        t.start()
    seen = sum(1 for _ in eng)
    stop.set()
    for t in readers:
        t.join()
    closers = [threading.Thread(target=eng.close) for _ in range(3)]
    for t in closers:
        t.start()
    for t in closers:
        t.join()
    if seen != total:
        raise SanitizeError(
            f"pipeline driver lost batches: {seen}/{total} emitted"
        )


def _drive_fleet(san: Graftsan) -> None:
    """Two-model FleetEngine with max_resident_models=1: every model swap
    is a page-in + LRU evict + batcher drain while other submitters keep
    routing — the lock-heaviest path in the repo. Engines are faked (no
    checkpoints, no compiler); the locks and the batchers are real."""
    import numpy as np

    from unittest import mock

    from ..serve.batcher import DynamicBatcher
    from ..serve.engine import InferenceEngine
    from ..serve.fleet.engine import FleetEngine
    from ..serve.fleet.registry import ModelRegistry, ModelSpec
    from ..serve.metrics import MetricsHub, ServeMetrics

    san.watch(FleetEngine)
    san.watch(DynamicBatcher)
    san.watch(ServeMetrics)
    san.watch(MetricsHub)

    class _FakeEngine:
        input_shape = (4,)
        num_classes = 3

        def predict(self, images):
            time.sleep(0.0002)
            return np.zeros((images.shape[0], 3), np.float32)

        def warmup(self):
            pass

        def info(self):
            return {"backend": "fake"}

    # A registry over checkpoints that don't exist: bypass the scanning
    # __init__ and install the specs directly (resolve/default_id logic
    # stays the real code).
    reg = ModelRegistry.__new__(ModelRegistry)
    reg.expt_dirs = [Path("graftsan-fake-expt")]
    reg.specs = {
        f"level_{lvl}": ModelSpec(
            model_id=f"level_{lvl}",
            expt_dir=Path("graftsan-fake-expt"),
            level=lvl,
        )
        for lvl in (0, 1)
    }

    answered = [0]
    answered_mu = threading.Lock()

    with mock.patch.object(
        InferenceEngine,
        "from_experiment",
        staticmethod(lambda *a, **k: _FakeEngine()),
    ):
        fleet = FleetEngine(
            reg,
            max_resident_models=1,
            max_wait_ms=1.0,
            queue_depth=64,
        )

        def client(i):
            x = np.zeros((1, 4), np.float32)
            for k in range(30):
                # Alternate models so the 1-slot LRU churns constantly.
                model = f"level_{(i + k) % 2}"
                try:
                    fut, _r = fleet.submit(x, model=model)
                    fut.result(timeout=30)
                # graftlint: disable=broad-except -- shed load (draining/evicted batcher, failed straggler) is a legal per-request answer under 1-slot LRU churn; the sanitizer's subject is the locks, and zero total successes still fails the smoke below
                except Exception:
                    continue
                with answered_mu:
                    answered[0] += 1

        clients = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in clients:
            t.start()
        for t in clients:
            t.join()
        fleet.info()
        fleet.drain(deadline_s=10.0)
    if answered[0] == 0:
        raise SanitizeError("fleet driver answered zero requests")


def _custom_driver(spec: str):
    """Resolution is deferred to drive time on purpose: run_sanitize pays
    for the full static pass before driving, and a bad spec should not
    error only after that wait in tests that probe it directly."""

    def drive(_san: Graftsan) -> None:
        builder, _paths = load_builder(
            spec, error_cls=SanitizeError, what="--sanitize target"
        )
        fn = builder()
        if callable(fn):
            fn()

    return drive


# ------------------------------------------------------------------- runner


def _static_keys() -> set:
    """(class, attr) keys the static layer already claims (waived findings
    included — a reviewed waiver is an explanation, not a blind spot)."""
    from .concurrency_rules import static_race_keys
    from .core import analyze_project

    result = analyze_project([_PKG_ROOT], jobs=1)
    return static_race_keys(result.findings)


def run_sanitize(target: str) -> int:
    target = target or "all"
    if target == "all":
        drivers = [("pipeline", _drive_pipeline), ("fleet", _drive_fleet)]
    else:
        kind, payload = resolve_runtime_target(
            target,
            {"pipeline": _drive_pipeline, "fleet": _drive_fleet},
            error_cls=SanitizeError,
            what="--sanitize target",
            load=False,  # builder modules must load inside the patched window
        )
        drivers = [
            (target, payload if kind == "named" else _custom_driver(target))
        ]

    # Static pass FIRST (it forks a process pool; keep that outside the
    # patched window) — its mutation keys are the explanation set.
    static = _static_keys()

    san = Graftsan()
    with san:
        for name, drive in drivers:
            t0 = time.perf_counter()
            drive(san)
            print(
                f"graftsan: drove {name} "
                f"({time.perf_counter() - t0:.2f}s, "
                f"{san.lock_count} package locks wrapped so far)"
            )

    cycles = san.cycles()
    races = san.races()
    unexplained = [
        r for r in races if (r["cls"], r["attr"]) not in static
    ]
    print(
        f"graftsan: {san.lock_count} locks wrapped, "
        f"{len(san.order_edges())} order edges, {len(cycles)} cycle(s), "
        f"{len(races)} observed race(s) ({len(unexplained)} unexplained)"
    )
    for c in cycles:
        print(f"graftsan: LOCK-ORDER CYCLE over {', '.join(c['locks'])}")
        for e in c["edges"]:
            print(f"    {e}")
    for r in races:
        tag = (
            "UNEXPLAINED (static blind spot)"
            if r in unexplained
            else "explained by a static finding"
        )
        print(
            f"graftsan: race on {r['cls']}.{r['attr']} — "
            f"{r['writes']} writes from {r['threads']} threads, {tag}"
        )
    return 1 if cycles or unexplained else 0
