"""Finding reporters: human text, machine JSON, and SARIF for CI.

Text goes to reviewers and CI logs (one grep-able line per finding, the
same ``path:line:col:`` shape compilers use, so editors jump to it). JSON
is the stable machine surface — its shape is pinned by
tests/test_analysis.py::test_json_reporter_shape, so downstream tooling
(dashboards, the check.sh gate, future pre-commit hooks) can rely on it.
SARIF 2.1.0 (``--format sarif``) is the lingua franca CI annotation
surface (GitHub code scanning et al.): unwaived findings become results,
waived ones carry an ``inSource`` suppression so they render as
acknowledged rather than vanish. Waived findings are REPORTED in every
format, not hidden: a waiver is an argued exception, and the reason
string travels with the finding so audits don't need to open the source.
"""

from __future__ import annotations

import json
from collections import Counter

from .core import RULES, AnalysisResult

__all__ = ["render_rule_docs", "render_text", "render_json", "render_sarif"]

# v2: findings gained "trace" (interprocedural call-path, null for
# per-file findings) when --project mode landed.
JSON_SCHEMA_VERSION = 2


def render_text(result: AnalysisResult, show_waived: bool = False) -> str:
    lines = []
    for f in result.findings:
        if f.waived and not show_waived:
            continue
        tag = " (waived: %s)" % (f.waiver_reason or "no reason given") if f.waived else ""
        trace = (
            " [call path: %s]" % " -> ".join(f.trace) if f.trace else ""
        )
        lines.append(
            f"{f.file}:{f.line}:{f.col + 1}: {f.rule} {f.severity}: "
            f"{f.message}{tag}{trace}"
        )
    for w in result.unused_waivers:
        lines.append(
            f"{w.file}:{w.line}: note: waiver for "
            f"{','.join(sorted(w.rules))} matched no finding — stale? "
            "(does not gate)"
        )
    n_unwaived = len(result.unwaived)
    n_waived = len(result.waived)
    lines.append(
        f"graftlint: {n_unwaived} finding(s) "
        f"({n_waived} waived) in {result.files_analyzed} file(s)"
    )
    return "\n".join(lines)


def _md_cell(text: str) -> str:
    return " ".join(str(text).split()).replace("|", "\\|")


def render_rule_docs() -> str:
    """The README rule-catalog table, generated from the registries so the
    docs can never drift from the code (``--rule-docs``; the self-gate in
    tests/test_analysis.py diffs this against README.md's marked block).
    Project-scope rules are tagged in the severity column; ``doc_why`` is
    each rule's third-column rationale."""
    from .conf_rules import CONF_RULES

    lines = [
        "| Rule | Severity | Catches | Why it matters on TPU |",
        "|---|---|---|---|",
    ]

    def row(rule, project: bool) -> None:
        sev = rule.severity + (" (project)" if project else "")
        lines.append(
            f"| `{rule.id}` | {sev} | {_md_cell(rule.description)} | "
            f"{_md_cell(rule.doc_why)} |"
        )

    for rid in sorted(RULES):
        row(RULES[rid], RULES[rid].project_only)
    for rid in sorted(CONF_RULES):
        row(CONF_RULES[rid], True)
    return "\n".join(lines) + "\n"


def render_json(result: AnalysisResult) -> str:
    by_rule = Counter(f.rule for f in result.unwaived)
    payload = {
        "version": JSON_SCHEMA_VERSION,
        "files_analyzed": result.files_analyzed,
        "summary": {
            "unwaived": len(result.unwaived),
            "waived": len(result.waived),
            "by_rule": dict(sorted(by_rule.items())),
        },
        "findings": [f.as_dict() for f in result.findings],
        "unused_waivers": [w.as_dict() for w in result.unused_waivers],
    }
    return json.dumps(payload, indent=2, sort_keys=False)


_SARIF_LEVELS = {"error": "error", "warning": "warning"}


def render_sarif(result: AnalysisResult) -> str:
    """SARIF 2.1.0, the minimal schema CI annotators consume."""
    from .conf_rules import CONF_RULES

    catalog = {**{r.id: r for r in RULES.values()}, **CONF_RULES}
    seen_rules = sorted({f.rule for f in result.findings})
    rules = [
        {
            "id": rid,
            "shortDescription": {
                "text": getattr(catalog.get(rid), "description", "") or rid
            },
        }
        for rid in seen_rules
    ]
    rule_index = {rid: i for i, rid in enumerate(seen_rules)}
    results = []
    for f in result.findings:
        entry = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": _SARIF_LEVELS.get(f.severity, "warning"),
            "message": {
                "text": f.message
                + (
                    " [call path: %s]" % " -> ".join(f.trace)
                    if f.trace
                    else ""
                )
            },
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.file},
                        "region": {
                            "startLine": f.line,
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.waived:
            entry["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": f.waiver_reason or "no reason given",
                }
            ]
        results.append(entry)
    payload = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(payload, indent=2, sort_keys=False)
