"""Shape lattice + abstract interpretation for the shape-flow rules.

Every executable this repo caches — AOT bucket executables, compact-train
step bundles, N:M plan programs — is keyed, directly or indirectly, by
input SHAPES. A dim that varies where the code assumed it was fixed is a
recompile; a dim that collides where the code assumed it distinguished is
a wrong executable served. This module gives the rules in shape_rules.py
(and the exec_manifest/compile_audit pair) a static approximation of how
shapes flow through a function:

* a small shape lattice — a shape is a tuple of dims where each dim is a
  known ``int``, a symbolic name (``"n"``, ``"x:0"``), or ``"?"``; a whole
  shape may also be unknown-rank (``None``). :func:`join_shape` joins
  pointwise (mismatched ranks collapse to unknown) and
  :func:`broadcast_shapes` models numpy-style right-aligned broadcasting;
* :class:`ScopeShapes`, a single-pass abstract interpreter over a function
  body (same architecture as dtype_flow.ScopeDtypes: assignments flow,
  branches join, loop bodies run twice). It tracks ``.shape``
  destructuring (``b, h, w, c = x.shape`` mints symbolic dims and
  back-propagates the learned rank onto ``x``), ``reshape(-1)`` with the
  product folded when every dim is known, broadcasting joins on binary
  ops, the axis ADDS of ``stack`` / ``expand_dims`` / ``x[None]`` /
  single-operand ``jax.vmap(lambda ...)``, the axis CONCATS of
  ``concatenate``/``hstack``/``vstack``, and ``lax.scan``'s carry-shape
  contract (carry keeps the init's shape; stacked ys are honest ``?``).

Dims carry provenance: a :class:`DimVal` remembers which array name it was
derived from (``src``), so a rule can ask "does this branch condition
depend on a dim of a TRACED param" without re-walking the expression.

Everything here is stdlib ``ast`` — same no-jax-at-import contract as the
rest of the package. The model is deliberately an approximation: ``?`` is
the honest default, rules only fire on KNOWN disagreements, so precision
errs toward silence, never toward false findings.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional, Union

from .regions import dotted_name

__all__ = [
    "DIM_UNKNOWN",
    "ArrayVal",
    "DimVal",
    "ShapeTupleVal",
    "dim_known",
    "join_dim",
    "join_shape",
    "broadcast_shapes",
    "shape_product",
    "ScopeShapes",
]

# ------------------------------------------------------------------ lattice

DIM_UNKNOWN = "?"

Dim = Union[int, str]  # int = known; str = symbolic name or "?"


def dim_known(d: Dim) -> bool:
    return isinstance(d, int)


def join_dim(a: Dim, b: Dim) -> Dim:
    """Equal dims (same int, same symbol) survive a join; anything else
    is ``?`` — two branches that disagree about a dim make it unknown."""
    return a if a == b else DIM_UNKNOWN


def join_shape(a: Optional[tuple], b: Optional[tuple]) -> Optional[tuple]:
    """Pointwise join; unknown rank absorbs, mismatched ranks collapse."""
    if a is None or b is None:
        return None
    if len(a) != len(b):
        return None
    return tuple(join_dim(x, y) for x, y in zip(a, b))


def broadcast_shapes(a: Optional[tuple], b: Optional[tuple]) -> Optional[tuple]:
    """numpy-style right-aligned broadcast of two shapes. A known-1 dim
    yields to the other side; equal dims (int or symbol) pass through;
    a known/symbolic disagreement is ``?`` (we approximate, never error)."""
    if a is None or b is None:
        return None
    out = []
    for i in range(max(len(a), len(b))):
        x = a[len(a) - 1 - i] if i < len(a) else 1
        y = b[len(b) - 1 - i] if i < len(b) else 1
        if x == 1:
            out.append(y)
        elif y == 1:
            out.append(x)
        elif x == y:
            out.append(x)
        else:
            out.append(DIM_UNKNOWN)
    return tuple(reversed(out))


def shape_product(shape: Optional[tuple]) -> Dim:
    """Element count: known iff every dim is known (``reshape(-1)``)."""
    if shape is None:
        return DIM_UNKNOWN
    n = 1
    for d in shape:
        if not dim_known(d):
            return DIM_UNKNOWN
        n *= d
    return n


# ------------------------------------------------------- abstract values


@dataclasses.dataclass(frozen=True)
class ArrayVal:
    """An array with ``shape`` (tuple of dims, or None = unknown rank) and
    ``src``, the name it was seeded/derived from (provenance for rules)."""

    shape: Optional[tuple] = None
    src: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class DimVal:
    """A host integer that is (or is derived from) an array dimension.
    ``src`` names the array it came from, None for plain literals."""

    dim: Dim = DIM_UNKNOWN
    src: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShapeTupleVal:
    """The value of ``x.shape`` itself: indexable, destructurable.
    ``dims`` None means the rank is unknown (symbolic dims are minted per
    index on demand)."""

    dims: Optional[tuple] = None  # tuple of Dim
    src: Optional[str] = None

    def item(self, i: int) -> DimVal:
        if self.dims is not None and -len(self.dims) <= i < len(self.dims):
            return DimVal(self.dims[i], self.src)
        sym = f"{self.src}:{i}" if self.src else DIM_UNKNOWN
        return DimVal(sym, self.src)


UNKNOWN = None  # absent knowledge: not an array, not a dim, nothing tracked


def _dim_of(v) -> Dim:
    if isinstance(v, DimVal):
        return v.dim
    return DIM_UNKNOWN


def _src_of(*vals) -> Optional[str]:
    for v in vals:
        s = getattr(v, "src", None)
        if s:
            return s
    return None


# ------------------------------------------------- call-name recognition


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _root(name: Optional[str]) -> Optional[str]:
    return name.split(".", 1)[0] if name else None


def _is_jnp(name: Optional[str]) -> bool:
    if not name:
        return False
    return (
        _root(name) in ("jnp", "np", "numpy", "onp", "nn")
        or name.startswith("jax.numpy.")
        or name.startswith("jax.nn.")
    )


def _is_lax(name: Optional[str]) -> bool:
    return bool(name) and "lax" in name.split(".")


_CREATION = {"zeros", "ones", "empty", "full"}
_LIKE = {"zeros_like", "ones_like", "empty_like", "full_like"}
_SHAPE_PASS = {
    # elementwise / dtype-ish ops that keep the operand's shape
    "exp", "log", "sqrt", "rsqrt", "tanh", "sin", "cos", "abs", "negative",
    "square", "sign", "relu", "gelu", "sigmoid", "softmax", "log_softmax",
    "clip", "astype", "asarray", "array", "stop_gradient", "nan_to_num",
    "sort", "flip", "roll", "copy", "where",
}
_CONCAT = {"concatenate", "hstack", "vstack"}
_AXIS_ADD = {"stack"}
_RANK_CHANGERS = {
    "reshape", "ravel", "flatten", "squeeze", "expand_dims",
    "atleast_1d", "atleast_2d", "atleast_3d",
}


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _const_int(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_int(node.operand)
        return -inner if inner is not None else None
    return None


# ------------------------------------------------- the abstract interpreter


class ScopeShapes:
    """One forward pass over a function (or module) body: every expression
    node gets an abstract value in ``self.at`` (keyed by ``id(node)``), and
    top-level ``return`` statements collect in ``self.returns``.

    Seed with ``{param: ArrayVal(None, src=param)}`` to mark traced array
    params; ``.shape`` access on them mints provenance-carrying DimVals.
    Mirrors dtype_flow.ScopeDtypes: nested defs run with a copied env,
    branches join, loop bodies run twice for loop-carried names.
    """

    def __init__(self, scope: Optional[ast.AST], seed: Optional[dict] = None):
        self.at: dict = {}
        self.returns: list = []  # (Return node, abstract value)
        env = dict(seed or {})
        if scope is None:
            return
        if isinstance(scope, ast.Module):
            self._exec_block(scope.body, env, top=True)
        elif isinstance(scope, ast.Lambda):
            v = self._infer(scope.body, env)
            self.returns.append((scope.body, v))
        else:  # FunctionDef / AsyncFunctionDef
            for p in self._params(scope):
                env.setdefault(p, UNKNOWN)
            self._exec_block(scope.body, env, top=True)

    # ---------------------------------------------------------------- query

    def value_of(self, node: ast.AST):
        return self.at.get(id(node), UNKNOWN)

    def shape_of(self, node: ast.AST) -> Optional[tuple]:
        v = self.value_of(node)
        return v.shape if isinstance(v, ArrayVal) else None

    # -------------------------------------------------------------- helpers

    @staticmethod
    def _params(fn: ast.AST) -> list:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def _assign_target(self, target: ast.AST, val, env: dict) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, UNKNOWN, env)
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = None
            if isinstance(val, ShapeTupleVal):
                items = [val.item(i) for i in range(len(target.elts))]
            for i, elt in enumerate(target.elts):
                self._assign_target(elt, items[i] if items else UNKNOWN, env)
        # attribute/subscript targets: no tracked binding

    def _assign(self, target: ast.AST, value: ast.AST, env: dict) -> None:
        if isinstance(target, (ast.Tuple, ast.List)) and isinstance(
            value, (ast.Tuple, ast.List)
        ) and len(target.elts) == len(value.elts):
            for t, v in zip(target.elts, value.elts):
                self._assign(t, v, env)
            return
        v = self._infer(value, env)
        # carry, ys = lax.scan(f, init, xs): the scan contract pins the
        # carry to the init's shape across every step; the stacked ys are
        # honestly unknown (their lead dim is the scan length).
        if (
            isinstance(target, (ast.Tuple, ast.List))
            and len(target.elts) == 2
            and isinstance(value, ast.Call)
            and _tail(dotted_name(value.func)) == "scan"
            and _is_lax(dotted_name(value.func))
            and len(value.args) >= 2
        ):
            init_v = self.value_of(value.args[1])
            self._assign_target(
                target.elts[0],
                init_v if isinstance(init_v, ArrayVal) else UNKNOWN,
                env,
            )
            self._assign_target(target.elts[1], UNKNOWN, env)
            return
        # b, h, w, c = x.shape  on an unknown-rank x: we just LEARNED x's
        # rank — mint symbolic dims named after the targets and
        # back-propagate the shape onto x itself.
        if (
            isinstance(target, (ast.Tuple, ast.List))
            and isinstance(v, ShapeTupleVal)
            and v.dims is None
            and isinstance(value, ast.Attribute)
            and value.attr == "shape"
            and isinstance(value.value, ast.Name)
            and not any(isinstance(t, ast.Starred) for t in target.elts)
        ):
            arr_name = value.value.id
            dims = tuple(
                t.id if isinstance(t, ast.Name) and t.id != "_" else DIM_UNKNOWN
                for t in target.elts
            )
            env[arr_name] = ArrayVal(dims, src=arr_name)
            v = ShapeTupleVal(dims, src=arr_name)
        self._assign_target(target, v, env)

    # ----------------------------------------------------------- statements

    def _exec_block(self, stmts: Iterable, env: dict, top: bool) -> None:
        for stmt in stmts:
            self._exec(stmt, env, top)

    def _exec(self, stmt: ast.AST, env: dict, top: bool) -> None:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._assign(t, stmt.value, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, stmt.value, env)
        elif isinstance(stmt, ast.AugAssign):
            v = self._infer(stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                cur = env.get(stmt.target.id, UNKNOWN)
                env[stmt.target.id] = self._binop(stmt.op, cur, v)
        elif isinstance(stmt, ast.Return):
            v = self._infer(stmt.value, env) if stmt.value is not None else UNKNOWN
            if top:
                self.returns.append((stmt, v))
        elif isinstance(stmt, ast.Expr):
            self._infer(stmt.value, env)
        elif isinstance(stmt, ast.If):
            self._infer(stmt.test, env)
            a, b = dict(env), dict(env)
            self._exec_block(stmt.body, a, top)
            self._exec_block(stmt.orelse, b, top)
            for k in set(a) | set(b):
                env[k] = self._join_vals(a.get(k, UNKNOWN), b.get(k, UNKNOWN))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._infer(stmt.iter, env)
            self._assign_target(stmt.target, UNKNOWN, env)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.orelse, env, top)
        elif isinstance(stmt, ast.While):
            self._infer(stmt.test, env)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.body, env, top)
            self._exec_block(stmt.orelse, env, top)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._infer(item.context_expr, env)
            self._exec_block(stmt.body, env, top)
        elif isinstance(stmt, ast.Try):
            self._exec_block(stmt.body, env, top)
            for h in stmt.handlers:
                self._exec_block(h.body, env, top)
            self._exec_block(stmt.orelse, env, top)
            self._exec_block(stmt.finalbody, env, top)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = dict(env)
            for p in self._params(stmt):
                inner[p] = UNKNOWN
            self._exec_block(stmt.body, inner, top=False)
        # ClassDef / imports / pass / etc: nothing to track

    @staticmethod
    def _join_vals(a, b):
        if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
            return ArrayVal(join_shape(a.shape, b.shape), a.src if a.src == b.src else None)
        if isinstance(a, DimVal) and isinstance(b, DimVal):
            return DimVal(join_dim(a.dim, b.dim), a.src if a.src == b.src else None)
        if type(a) is type(b) and a == b:
            return a
        return UNKNOWN

    # ---------------------------------------------------------- expressions

    def _infer(self, node: Optional[ast.AST], env: dict):
        if node is None:
            return UNKNOWN
        v = self._infer_inner(node, env)
        self.at[id(node)] = v
        return v

    def _infer_inner(self, node: ast.AST, env: dict):
        if isinstance(node, ast.Constant):
            if isinstance(node.value, int) and not isinstance(node.value, bool):
                return DimVal(node.value)
            return UNKNOWN
        if isinstance(node, ast.Name):
            return env.get(node.id, UNKNOWN)
        if isinstance(node, ast.BinOp):
            return self._binop(
                node.op,
                self._infer(node.left, env),
                self._infer(node.right, env),
            )
        if isinstance(node, ast.UnaryOp):
            v = self._infer(node.operand, env)
            if isinstance(node.op, ast.USub) and isinstance(v, DimVal) and dim_known(v.dim):
                return DimVal(-v.dim, v.src)
            return v
        if isinstance(node, ast.IfExp):
            self._infer(node.test, env)
            return self._join_vals(
                self._infer(node.body, env), self._infer(node.orelse, env)
            )
        if isinstance(node, ast.Compare):
            self._infer(node.left, env)
            for c in node.comparators:
                self._infer(c, env)
            return UNKNOWN
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._infer(v, env)
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env)
        if isinstance(node, ast.Attribute):
            return self._attribute(node, env)
        if isinstance(node, (ast.Tuple, ast.List)):
            items = tuple(self._infer(e, env) for e in node.elts)
            # a literal tuple of dims doubles as a shape-tuple value
            if items and all(isinstance(i, DimVal) for i in items):
                return ShapeTupleVal(tuple(i.dim for i in items), _src_of(*items))
            return UNKNOWN
        if isinstance(node, ast.Call):
            return self._infer_call(node, env)
        if isinstance(node, ast.Lambda):
            inner = dict(env)
            for p in self._params(node):
                inner[p] = UNKNOWN
            self._infer(node.body, inner)
            return UNKNOWN
        if isinstance(node, ast.Starred):
            return self._infer(node.value, env)
        return UNKNOWN

    @staticmethod
    def _binop(op: ast.AST, a, b):
        if isinstance(a, ArrayVal) or isinstance(b, ArrayVal):
            # array (x) array broadcasts; array (x) scalar keeps the shape
            sa = a.shape if isinstance(a, ArrayVal) else ()
            sb = b.shape if isinstance(b, ArrayVal) else ()
            if not isinstance(a, ArrayVal):
                return ArrayVal(sb, getattr(b, "src", None))
            if not isinstance(b, ArrayVal):
                return ArrayVal(sa, a.src)
            return ArrayVal(broadcast_shapes(sa, sb))
        if isinstance(a, DimVal) and isinstance(b, DimVal):
            if dim_known(a.dim) and dim_known(b.dim):
                try:
                    if isinstance(op, ast.Add):
                        return DimVal(a.dim + b.dim, _src_of(a, b))
                    if isinstance(op, ast.Sub):
                        return DimVal(a.dim - b.dim, _src_of(a, b))
                    if isinstance(op, ast.Mult):
                        return DimVal(a.dim * b.dim, _src_of(a, b))
                    if isinstance(op, ast.FloorDiv) and b.dim != 0:
                        return DimVal(a.dim // b.dim, _src_of(a, b))
                except (OverflowError, ValueError):  # pragma: no cover
                    pass
            return DimVal(DIM_UNKNOWN, _src_of(a, b))
        if isinstance(a, DimVal) or isinstance(b, DimVal):
            d = a if isinstance(a, DimVal) else b
            return DimVal(DIM_UNKNOWN, d.src)
        return UNKNOWN

    def _subscript(self, node: ast.Subscript, env: dict):
        recv = self._infer(node.value, env)
        sl = node.slice
        if isinstance(recv, ShapeTupleVal):
            self._infer(sl, env)
            i = _const_int(sl)
            if i is not None:
                return recv.item(i)
            return DimVal(DIM_UNKNOWN, recv.src)
        if isinstance(recv, ArrayVal):
            return self._index_array(recv, sl, env)
        self._infer(sl, env)
        return UNKNOWN

    def _index_array(self, arr: ArrayVal, sl: ast.AST, env: dict) -> ArrayVal:
        """One indexing step on an array: int index drops the axis, a slice
        rewrites it, ``None`` adds one, a tuple applies per-axis."""
        parts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        if arr.shape is None:
            for p in parts:
                self._infer(p, env)
            return ArrayVal(None, arr.src)
        dims = list(arr.shape)
        out: list = []
        pos = 0
        for p in parts:
            if isinstance(p, ast.Constant) and p.value is None:
                out.append(1)
                continue
            if pos >= len(dims):
                return ArrayVal(None, arr.src)
            if isinstance(p, ast.Slice):
                out.append(self._slice_dim(dims[pos], p, env))
                pos += 1
            else:
                v = self._infer(p, env)
                if isinstance(v, ArrayVal):  # fancy indexing: give up
                    return ArrayVal(None, arr.src)
                pos += 1  # int index: axis dropped
        out.extend(dims[pos:])
        return ArrayVal(tuple(out), arr.src)

    def _slice_dim(self, dim: Dim, sl: ast.Slice, env: dict) -> Dim:
        lo = self._infer(sl.lower, env) if sl.lower else None
        hi = self._infer(sl.upper, env) if sl.upper else None
        self._infer(sl.step, env)
        if sl.step is not None:
            return DIM_UNKNOWN
        if sl.lower is None and sl.upper is None:
            return dim
        if sl.lower is None and isinstance(hi, DimVal):
            # x[:k] — length k when k is known or symbolic (abstractly: the
            # slice length IS the bound, assuming k <= dim)
            return hi.dim
        if sl.upper is None and isinstance(lo, DimVal) and dim_known(dim) and dim_known(lo.dim):
            return max(dim - lo.dim, 0)
        return DIM_UNKNOWN

    def _attribute(self, node: ast.Attribute, env: dict):
        recv = self._infer(node.value, env)
        if isinstance(recv, ArrayVal):
            if node.attr == "shape":
                return ShapeTupleVal(recv.shape, recv.src)
            if node.attr == "ndim":
                if recv.shape is not None:
                    return DimVal(len(recv.shape), recv.src)
                return DimVal(DIM_UNKNOWN, recv.src)
            if node.attr == "size":
                return DimVal(shape_product(recv.shape), recv.src)
            if node.attr == "T":
                s = tuple(reversed(recv.shape)) if recv.shape is not None else None
                return ArrayVal(s, recv.src)
            if node.attr in ("real", "imag", "at"):
                return recv
        return UNKNOWN

    # ------------------------------------------------------------- calls

    def _reshape_result(self, call: ast.Call, base: ArrayVal, args: list, env: dict) -> ArrayVal:
        """Target dims of ``reshape``: fold ``-1`` from the element count
        when every other dim (and the source shape) is known."""
        if len(args) == 1:
            v = self._infer(args[0], env)
            if isinstance(v, ShapeTupleVal) and v.dims is not None:
                dims = list(v.dims)
            elif isinstance(v, DimVal):
                dims = [v.dim]
            else:
                return ArrayVal(None, base.src)
        else:
            dims = []
            for a in args:
                v = self._infer(a, env)
                dims.append(v.dim if isinstance(v, DimVal) else DIM_UNKNOWN)
        if -1 in dims:
            total = shape_product(base.shape)
            rest = 1
            ok = dim_known(total)
            for d in dims:
                if d == -1:
                    continue
                if not dim_known(d):
                    ok = False
                    break
                rest *= d
            i = dims.index(-1)
            dims[i] = (total // rest) if (ok and rest) else DIM_UNKNOWN
        return ArrayVal(tuple(dims), base.src)

    def _infer_call(self, node: ast.Call, env: dict):
        f = node.func
        name = dotted_name(f)
        tail = _tail(name)

        # method calls on a value we track: x.reshape(...), x.astype(...)
        recv = UNKNOWN
        if isinstance(f, ast.Attribute):
            recv = self._infer(f.value, env)
        if isinstance(recv, ArrayVal):
            if f.attr in _RANK_CHANGERS:
                for kw in node.keywords:
                    self._infer(kw.value, env)
                return self._method_rank_change(node, f.attr, recv, env)
            if f.attr in ("astype", "copy", "clip", "sort", "block_until_ready"):
                for a in node.args:
                    self._infer(a, env)
                for kw in node.keywords:
                    self._infer(kw.value, env)
                return recv
            if f.attr in ("sum", "mean", "prod", "max", "min", "var", "std"):
                for a in node.args:
                    self._infer(a, env)
                for kw in node.keywords:
                    self._infer(kw.value, env)
                if not node.args and not node.keywords:
                    return ArrayVal(())
                return ArrayVal(None, recv.src)

        argv = [self._infer(a, env) for a in node.args]
        for kw in node.keywords:
            self._infer(kw.value, env)

        if name == "len" and len(argv) == 1:
            v = argv[0]
            if isinstance(v, ArrayVal):
                if v.shape is not None and v.shape:
                    return DimVal(v.shape[0], v.src)
                return DimVal(DIM_UNKNOWN, v.src)
            if isinstance(v, ShapeTupleVal):
                if v.dims is not None:
                    return DimVal(len(v.dims), v.src)
                return DimVal(DIM_UNKNOWN, v.src)
            return UNKNOWN
        if name == "int" and len(argv) == 1 and isinstance(argv[0], DimVal):
            return argv[0]

        # vmap adds a leading axis: jax.vmap(lambda v: body)(x)
        if (
            isinstance(f, ast.Call)
            and _tail(dotted_name(f.func)) == "vmap"
            and len(node.args) == 1
            and isinstance(argv[0], ArrayVal)
        ):
            return self._vmap_result(f, argv[0], env)

        if not _is_jnp(name) and not _is_lax(name):
            if isinstance(f, ast.Attribute) and f.attr == "astype":
                recv = self.value_of(f.value)
                if isinstance(recv, ArrayVal):
                    return recv
            if tail == "scan" and _is_lax(name) and len(node.args) >= 2:
                # carry keeps the init's shape (the scan contract); the
                # stacked ys are honestly unknown
                return UNKNOWN
            return UNKNOWN

        if tail == "scan" and len(argv) >= 2:
            return UNKNOWN  # (carry, ys) tuple: callers read via unpacking
        if tail in _CREATION:
            shape_arg = node.args[0] if node.args else _kw(node, "shape")
            if shape_arg is not None:
                v = self.value_of(shape_arg) if id(shape_arg) in self.at else self._infer(shape_arg, env)
                if isinstance(v, ShapeTupleVal) and v.dims is not None:
                    return ArrayVal(v.dims)
                if isinstance(v, DimVal):
                    return ArrayVal((v.dim,))
            return ArrayVal(None)
        if tail in _LIKE and argv:
            v = argv[0]
            return v if isinstance(v, ArrayVal) else ArrayVal(None)
        if tail == "arange" and argv:
            v = argv[0]
            if len(node.args) == 1 and isinstance(v, DimVal):
                return ArrayVal((v.dim,), v.src)
            return ArrayVal((DIM_UNKNOWN,))
        if tail == "reshape" and node.args:
            base = argv[0]
            if isinstance(base, ArrayVal):
                return self._reshape_result(node, base, node.args[1:], env)
            return UNKNOWN
        if tail == "expand_dims" and argv:
            base = argv[0]
            if isinstance(base, ArrayVal):
                return self._expand_dims(base, node, env)
            return UNKNOWN
        if tail == "squeeze" and argv and isinstance(argv[0], ArrayVal):
            return self._squeeze(argv[0], node)
        if tail in ("ravel", "flatten") and argv and isinstance(argv[0], ArrayVal):
            return ArrayVal((shape_product(argv[0].shape),), argv[0].src)
        if tail in _CONCAT and node.args:
            return self._concat(tail, node, env)
        if tail in _AXIS_ADD and node.args:
            return self._stack(node, env)
        if tail == "broadcast_to" and len(node.args) >= 2:
            v = self.value_of(node.args[1])
            if isinstance(v, ShapeTupleVal) and v.dims is not None:
                return ArrayVal(v.dims)
            return ArrayVal(None)
        if tail == "matmul" or tail == "dot":
            a, b = (argv + [UNKNOWN, UNKNOWN])[:2]
            if (
                isinstance(a, ArrayVal) and isinstance(b, ArrayVal)
                and a.shape is not None and b.shape is not None
                and len(a.shape) == 2 and len(b.shape) == 2
            ):
                return ArrayVal((a.shape[0], b.shape[1]))
            return ArrayVal(None)
        if tail == "where" and len(argv) >= 3:
            x, y = argv[1], argv[2]
            if isinstance(x, ArrayVal) and isinstance(y, ArrayVal):
                return ArrayVal(broadcast_shapes(x.shape, y.shape))
            return argv[1] if isinstance(argv[1], ArrayVal) else UNKNOWN
        if tail in _SHAPE_PASS and argv:
            v = argv[0]
            return v if isinstance(v, ArrayVal) else UNKNOWN
        if tail in ("sum", "mean", "prod", "max", "min", "var", "std") and argv:
            v = argv[0]
            if isinstance(v, ArrayVal):
                axis = _kw(node, "axis")
                if axis is None and len(node.args) < 2:
                    return ArrayVal(())  # full reduction: scalar
                return ArrayVal(None, v.src)
            return UNKNOWN
        if tail == "pad" and argv and isinstance(argv[0], ArrayVal):
            # padded dims are data-dependent on the pad widths: honest ?
            s = argv[0].shape
            return ArrayVal(tuple(DIM_UNKNOWN for _ in s) if s is not None else None, argv[0].src)
        return UNKNOWN

    def _method_rank_change(self, node: ast.Call, attr: str, recv: ArrayVal, env: dict):
        if attr == "reshape":
            return self._reshape_result(node, recv, node.args, env)
        if attr in ("ravel", "flatten", "atleast_1d"):
            for a in node.args:
                self._infer(a, env)
            return ArrayVal((shape_product(recv.shape),), recv.src)
        if attr == "squeeze":
            for a in node.args:
                self._infer(a, env)
            return self._squeeze(recv, node)
        if attr == "expand_dims":
            return self._expand_dims(recv, node, env)
        for a in node.args:
            self._infer(a, env)
        return ArrayVal(None, recv.src)

    def _expand_dims(self, base: ArrayVal, node: ast.Call, env: dict) -> ArrayVal:
        axis_node = _kw(node, "axis")
        if axis_node is None:
            # positional: jnp.expand_dims(x, ax) or x.expand_dims(ax)
            pos = node.args[1:] if self.value_of(node.args[0]) is base else node.args
            axis_node = pos[0] if pos else None
        ax = _const_int(axis_node) if axis_node is not None else None
        if base.shape is None or ax is None:
            return ArrayVal(None, base.src)
        dims = list(base.shape)
        if ax < 0:
            ax += len(dims) + 1
        if 0 <= ax <= len(dims):
            dims.insert(ax, 1)
            return ArrayVal(tuple(dims), base.src)
        return ArrayVal(None, base.src)

    @staticmethod
    def _squeeze(base: ArrayVal, node: ast.Call) -> ArrayVal:
        if base.shape is None:
            return ArrayVal(None, base.src)
        if any(not dim_known(d) for d in base.shape):
            # can't prove which axes are 1
            return ArrayVal(None, base.src)
        return ArrayVal(tuple(d for d in base.shape if d != 1), base.src)

    def _concat(self, tail: str, node: ast.Call, env: dict):
        seq = node.args[0]
        if not isinstance(seq, (ast.Tuple, ast.List)) or not seq.elts:
            return ArrayVal(None)
        vals = [self.value_of(e) for e in seq.elts]
        if not all(isinstance(v, ArrayVal) for v in vals):
            return ArrayVal(None)
        shapes = [v.shape for v in vals]
        if any(s is None for s in shapes):
            return ArrayVal(None)
        rank = len(shapes[0])
        if any(len(s) != rank for s in shapes):
            return ArrayVal(None)
        axis_node = _kw(node, "axis")
        if axis_node is None and len(node.args) >= 2:
            axis_node = node.args[1]
        ax = _const_int(axis_node) if axis_node is not None else 0
        if tail == "vstack":
            ax = 0
        elif tail == "hstack":
            ax = 0 if rank == 1 else 1
        if ax is None:
            return ArrayVal(None)
        if ax < 0:
            ax += rank
        if not 0 <= ax < rank:
            return ArrayVal(None)
        out: list = []
        for i in range(rank):
            dims = [s[i] for s in shapes]
            if i == ax:
                if all(dim_known(d) for d in dims):
                    out.append(sum(dims))
                else:
                    out.append(DIM_UNKNOWN)
            else:
                d = dims[0]
                for other in dims[1:]:
                    d = join_dim(d, other)
                out.append(d)
        return ArrayVal(tuple(out))

    def _stack(self, node: ast.Call, env: dict):
        seq = node.args[0]
        if not isinstance(seq, (ast.Tuple, ast.List)) or not seq.elts:
            return ArrayVal(None)
        vals = [self.value_of(e) for e in seq.elts]
        if not all(isinstance(v, ArrayVal) for v in vals):
            return ArrayVal(None)
        inner = vals[0].shape
        for v in vals[1:]:
            inner = join_shape(inner, v.shape)
        if inner is None:
            return ArrayVal(None)
        return ArrayVal((len(vals), *inner))

    def _vmap_result(self, vmap_call: ast.Call, operand: ArrayVal, env: dict):
        """``jax.vmap(f)(x)``: the mapped axis is re-added in front of
        whatever ``f`` returns for one slice. Resolvable only when ``f``
        is a lambda (body inferable); else the lead dim alone is kept."""
        lead = operand.shape[0] if operand.shape else DIM_UNKNOWN
        fn = vmap_call.args[0] if vmap_call.args else None
        if isinstance(fn, ast.Lambda):
            params = self._params(fn)
            inner_env = dict(env)
            if params:
                sliced = ArrayVal(
                    operand.shape[1:] if operand.shape else None, params[0]
                )
                inner_env[params[0]] = sliced
                for p in params[1:]:
                    inner_env[p] = UNKNOWN
            body = self._infer(fn.body, inner_env)
            if isinstance(body, ArrayVal) and body.shape is not None:
                return ArrayVal((lead, *body.shape))
        if operand.shape is not None:
            return ArrayVal(None)
        return ArrayVal(None)
