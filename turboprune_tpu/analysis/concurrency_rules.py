"""Concurrency rules: the thread model (threads.py) and lockset
interpretation (locks.py) turned into findings.

Five rules. Four are ``project_only`` — they need the symbol table, the
spawn-site closure, and the interprocedural acquisition graph, so they
fire exclusively from ``check_project`` (per-file mode skips them, and
per-file stale-waiver accounting treats their waivers as out of scope,
exactly like the conf rules). ``cv-wait-no-predicate-loop`` is lexical
and runs per-file like any other rule.

* ``unsynchronized-shared-mutation`` — a ``self.X`` written outside
  ``__init__`` in a thread-spawning class, where a write and another
  access can run on different threads with no common lock. When the field
  carries a ``# guarded-by: <lock>`` annotation the rule switches from
  heuristic to contract checking: EVERY access outside ``__init__`` must
  hold the named lock, thread model or not.
* ``lock-order-inversion`` — a cycle in the interprocedural
  lock-acquisition-order graph, including the degenerate self-cycle (a
  non-reentrant lock re-acquired while held: guaranteed deadlock).
* ``blocking-call-under-lock`` — device_put / AOT lower+compile /
  ``queue.get`` / ``time.sleep`` / socket I/O / ``Future.result`` /
  thread+pool joins while holding a tracked lock, directly or through a
  resolved callee (with the witness chain in the trace). ``Condition
  .wait()`` under its OWN condition is exempt — wait releases that lock.
* ``check-then-act-race`` — ``if k not in self.d: self.d[k] = ...`` with
  an empty lockset, in classes that spawn threads (or functions inside a
  worker closure).
* ``cv-wait-no-predicate-loop`` — ``Condition.wait()`` whose innermost
  enclosing loop is not a ``while`` (spurious wakeups and stolen
  notifications; a ``for`` does not re-test the predicate).

The ``unsynchronized-shared-mutation`` message format is a stable
contract: the runtime sanitizer (sanitizer.py) parses it back into a
``(class, attribute)`` key via :func:`shared_mutation_key` to diff
runtime-observed races against the static findings.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from .core import RULES, Finding, ModuleContext, Rule, register
from .locks import (
    LockAnalysis,
    _assign_targets,
    build_order_graph,
    ctor_kind,
    cycle_witness,
    find_cycles,
)
from .regions import dotted_name
from .rules import _root, _tail
from .threads import CALLER, ThreadModel

__all__ = [
    "concurrency_findings",
    "shared_mutation_key",
    "static_race_keys",
]

_MAX_DEPTH = 10

_SOCKET_TAILS = {"recv", "recv_into", "accept", "connect", "sendall"}


# ------------------------------------------------------------ registration


class _ProjectConcurrencyRule(Rule):
    """Needs the thread model + lockset layer: project mode only."""

    project_only = True
    skip_in_tests = True

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        return iter(())


@register
class UnsynchronizedSharedMutationRule(_ProjectConcurrencyRule):
    id = "unsynchronized-shared-mutation"
    severity = "error"
    description = (
        "self.* attribute written on one thread and accessed on another "
        "with no common lock (or in violation of its # guarded-by: "
        "annotation)"
    )
    doc_why = (
        "torn and stale reads on the serving hot path — races that only "
        "reproduce under production load, never in single-threaded tests"
    )


@register
class LockOrderInversionRule(_ProjectConcurrencyRule):
    id = "lock-order-inversion"
    severity = "error"
    description = (
        "cycle in the interprocedural lock-acquisition-order graph "
        "(opposite-order deadlock, or a non-reentrant self-acquire)"
    )
    doc_why = (
        "two threads acquiring in opposite orders deadlock with no "
        "traceback — requests hang until the process is killed"
    )


@register
class BlockingCallUnderLockRule(_ProjectConcurrencyRule):
    id = "blocking-call-under-lock"
    severity = "warning"
    description = (
        "sleep/queue/socket/Future/AOT-compile blocking operation while "
        "holding a lock, directly or through a resolved callee"
    )
    doc_why = (
        "seconds of blocking work under a lock head-of-line-blocks every "
        "thread behind it — one cold-bucket compile can stall the whole "
        "serving fleet"
    )


@register
class CheckThenActRaceRule(_ProjectConcurrencyRule):
    id = "check-then-act-race"
    severity = "warning"
    description = (
        "unguarded 'if k not in self.d: self.d[k] = ...' in thread-aware "
        "code (both threads see 'missing', both insert)"
    )
    doc_why = (
        "both threads see the missing state and both act — double "
        "compiles, double closes, lost idempotence (the PrefetchEngine "
        "close() bug class)"
    )


@register
class CvWaitNoPredicateLoopRule(Rule):
    id = "cv-wait-no-predicate-loop"
    severity = "error"
    skip_in_tests = True
    description = (
        "Condition.wait() whose innermost enclosing loop is not a while "
        "(spurious wakeup / stolen notification loses the signal)"
    )
    doc_why = (
        "spurious wakeups and stolen signals are legal; an if-guarded "
        "wait proceeds on a false predicate — the classic lost-wakeup "
        "hang"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        cond_names: set = set()
        for node in ast.walk(ctx.tree):
            for target, value in _assign_targets(node):
                if ctor_kind(value) == "condition":
                    name = dotted_name(target)
                    if name:
                        cond_names.add(name)
        if not cond_names:
            return
        hits: list = []
        self._scan(ctx.tree, None, cond_names, hits)
        for node, recv in hits:
            yield ctx.finding(
                self,
                node,
                f"{recv}.wait() is not re-checked in a while loop — "
                "condition waits wake spuriously and notifications can be "
                f"consumed by another waiter; use 'while not <predicate>: "
                f"{recv}.wait()'",
            )

    def _scan(self, node, loop, cond_names, hits) -> None:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "wait"
        ):
            recv = dotted_name(node.func.value)
            if recv in cond_names and loop != "while":
                hits.append((node, recv))
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            kind = "while" if isinstance(node, ast.While) else "for"
            body = set(map(id, node.body))
            for child in ast.iter_child_nodes(node):
                self._scan(
                    child,
                    kind if id(child) in body else loop,
                    cond_names,
                    hits,
                )
            return
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            for child in ast.iter_child_nodes(node):
                self._scan(child, None, cond_names, hits)
            return
        for child in ast.iter_child_nodes(node):
            self._scan(child, loop, cond_names, hits)


# ------------------------------------------------------- sanitizer contract

_MUTATION_KEY_RE = re.compile(r"^self\.(\w+) of (\w+) ")


def shared_mutation_key(message: str) -> Optional[tuple]:
    """(class_name, attr) from an unsynchronized-shared-mutation message;
    the sanitizer uses this to match runtime-observed races to static
    findings (waived or not — a waiver is still an explanation)."""
    m = _MUTATION_KEY_RE.match(message)
    return (m.group(2), m.group(1)) if m else None


def static_race_keys(findings) -> set:
    """All (class_name, attr) keys claimed by static mutation findings."""
    out: set = set()
    for f in findings:
        if f.rule == UnsynchronizedSharedMutationRule.id:
            key = shared_mutation_key(f.message)
            if key:
                out.add(key)
    return out


# --------------------------------------------------------------- the checker


def _short_lock(lock_id: str) -> str:
    parts = lock_id.replace(".<local>", "").split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else lock_id


def _mk(rule_id: str, file: str, line: int, message: str, trace) -> Finding:
    rule = RULES[rule_id]
    return Finding(
        file=file,
        line=line,
        col=0,
        rule=rule_id,
        severity=rule.severity,
        message=message,
        trace=list(trace) or None,
    )


def _classify_blocking(call: ast.Call, fi, analysis) -> Optional[tuple]:
    """(label, own_condition_lock_id_or_None) for a directly-blocking
    call; None otherwise. The second slot is set only for
    ``Condition.wait()`` so the caller can exempt the condition's own
    lock (wait releases it while blocked)."""
    f = call.func
    name = dotted_name(f)
    tail = _tail(name)
    root = _root(name)
    if name:
        if tail == "sleep" and root in ("time", "sleep"):
            return ("time.sleep()", None)
        if tail in ("device_put", "device_get") and root in ("jax", tail):
            return (f"jax.{tail}()", None)
        if tail == "urlopen":
            return ("urlopen()", None)
    if not isinstance(f, ast.Attribute):
        return None
    a = f.attr
    if a == "block_until_ready":
        return (".block_until_ready()", None)
    if a == "lower" and (call.args or call.keywords):
        # jit(f).lower(sample) traces; str.lower() takes no arguments
        return ("AOT .lower()", None)
    if a == "compile" and root != "re":
        return ("AOT .compile()", None)
    if a == "result":
        return ("Future.result()", None)
    if a in _SOCKET_TAILS:
        return (f"socket .{a}()", None)
    hit = analysis.declared_kind(f.value, fi)
    if hit is None:
        return None
    rid, kind = hit
    if a in ("get", "put") and kind == "queue":
        return (f"queue .{a}()", None)
    if a == "join" and kind in ("thread", "pool"):
        return (f"{kind} .join()", None)
    if a == "shutdown" and kind == "pool":
        wait_false = any(
            kw.arg == "wait"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in call.keywords
        )
        if not wait_false:
            return ("pool .shutdown(wait=True)", None)
    if a == "wait" and kind in ("condition", "event"):
        return (f"{kind} .wait()", rid if kind == "condition" else None)
    return None


class _Checker:
    def __init__(self, index, contexts):
        self.index = index
        self.contexts = contexts
        self.analysis = LockAnalysis(index, contexts)
        self.model = ThreadModel(index, self.analysis.types)
        self._blk_memo: dict = {}

    def _functions(self):
        for qual in sorted(self.index.functions):
            yield self.index.functions[qual]

    # ------------------------------------------- unsynchronized mutation
    def mutation_findings(self) -> Iterator[Finding]:
        per_class: dict = {}
        for fi in self._functions():
            if fi.class_name is None or fi.name == "__init__":
                continue
            cq = f"{fi.modname}.{fi.class_name}"
            info = self.analysis.info(fi)
            for acc in info.accesses:
                per_class.setdefault(cq, {}).setdefault(
                    acc.attr, []
                ).append((fi, acc))
        for cq in sorted(per_class):
            cls = cq.rsplit(".", 1)[-1]
            for attr in sorted(per_class[cq]):
                accs = sorted(
                    per_class[cq][attr],
                    key=lambda t: (t[0].path, t[1].line),
                )
                guard = self.analysis.guards.get((cq, attr))
                if guard is not None:
                    yield from self._guard_violations(
                        cq, cls, attr, guard, accs
                    )
                    continue
                if cq not in self.model.spawning_classes:
                    continue
                yield from self._heuristic_conflict(cq, cls, attr, accs)

    def _guard_violations(self, cq, cls, attr, guard, accs):
        gid = f"{cq}.{guard}"
        bad = [(fi, a) for fi, a in accs if gid not in a.held]
        if not bad:
            return
        fi, a = bad[0]
        trace = [
            f"{bfi.name} ({bfi.path}:{ba.line}) "
            f"{'writes' if ba.write else 'reads'} without {guard}"
            for bfi, ba in bad[:4]
        ]
        yield _mk(
            UnsynchronizedSharedMutationRule.id,
            fi.path,
            a.line,
            f"self.{attr} of {cls} is declared '# guarded-by: {guard}' "
            f"but {fi.name}() accesses it without holding self.{guard} "
            f"({len(bad)} unguarded site(s)) — either take the lock or "
            "fix the annotation",
            trace,
        )

    def _heuristic_conflict(self, cq, cls, attr, accs):
        writes = [(fi, a) for fi, a in accs if a.write]
        for wfi, w in writes:
            wctx = self.model.contexts(wfi.qualname)
            if not wctx:
                continue
            for afi, a in accs:
                if a is w:
                    continue
                actx = self.model.contexts(afi.qualname)
                if not actx:
                    continue
                union = wctx | actx
                multi = len(union) > 1 or any(
                    c != CALLER and self.model.is_pool_target(c)
                    for c in union
                )
                if not multi or (w.held & a.held):
                    continue
                wlbl = self._ctx_label(wctx)
                albl = self._ctx_label(actx)
                trace = self._thread_trace(wfi, wctx)
                trace.append(
                    f"write: {wfi.name} ({wfi.path}:{w.line}) on {wlbl}"
                )
                trace.append(
                    f"conflicting "
                    f"{'write' if a.write else 'read'}: {afi.name} "
                    f"({afi.path}:{a.line}) on {albl}"
                )
                yield _mk(
                    UnsynchronizedSharedMutationRule.id,
                    wfi.path,
                    w.line,
                    f"self.{attr} of {cls} is written by {wfi.name}() on "
                    f"{wlbl} and accessed by {afi.name}() on {albl} with "
                    "no common lock — torn/lost update; guard both sides "
                    "with one lock and document it as "
                    "'# guarded-by: <lock>'",
                    trace,
                )
                return  # one finding per (class, attr)

    def _ctx_label(self, ctxs) -> str:
        return " / ".join(
            sorted(self.model.context_label(c) for c in ctxs)
        )

    def _thread_trace(self, fi, ctxs) -> list:
        for c in sorted(ctxs):
            if c != CALLER:
                return self.model.trace_to(fi.qualname, c)
        return [f"{fi.name} runs on the caller's thread"]

    # ------------------------------------------------ lock-order inversion
    def order_findings(self) -> Iterator[Finding]:
        edges = build_order_graph(self.analysis)
        for cycle in find_cycles(edges):
            wits = list(cycle_witness(cycle, edges))
            first = wits[0]
            if len(cycle) == 1:
                msg = (
                    f"lock-order cycle: non-reentrant "
                    f"{_short_lock(cycle[0])} is re-acquired while "
                    "already held — guaranteed self-deadlock; use an "
                    "RLock or split the critical section"
                )
            else:
                path = " -> ".join(
                    _short_lock(c) for c in cycle + [cycle[0]]
                )
                msg = (
                    f"lock-order cycle: {path} — threads taking these "
                    "locks in opposite orders deadlock; impose one "
                    "global acquisition order"
                )
            trace = [hop for e in wits for hop in e.witness][:8]
            yield _mk(
                LockOrderInversionRule.id, first.file, first.line, msg, trace
            )

    # --------------------------------------------- blocking call under lock
    def blocking_witness(self, fi, _depth: int = 0) -> Optional[list]:
        if fi.qualname in self._blk_memo:
            return self._blk_memo[fi.qualname]
        self._blk_memo[fi.qualname] = None  # cycle guard
        info = self.analysis.info(fi)
        calls = sorted(info.calls, key=lambda c: c.line)
        for cs in calls:
            hit = _classify_blocking(cs.node, fi, self.analysis)
            if hit is not None and hit[1] is None:
                wit = [f"{fi.name} calls {hit[0]} ({fi.path}:{cs.line})"]
                self._blk_memo[fi.qualname] = wit
                return wit
        if _depth >= _MAX_DEPTH:
            return None
        mi = self.index.modules.get(fi.modname)
        if mi is None:
            return None
        for cs in calls:
            callee = self.index.resolve_call(mi, cs.node.func, fi)
            if callee is None or callee.qualname == fi.qualname:
                continue
            sub = self.blocking_witness(callee, _depth + 1)
            if sub:
                wit = [
                    f"{fi.name} -> {callee.name} ({fi.path}:{cs.line})"
                ] + sub
                self._blk_memo[fi.qualname] = wit
                return wit
        return None

    def blocking_findings(self) -> Iterator[Finding]:
        for fi in self._functions():
            mi = self.index.modules.get(fi.modname)
            info = self.analysis.info(fi)
            for cs in sorted(info.calls, key=lambda c: c.line):
                if not cs.held:
                    continue
                hit = _classify_blocking(cs.node, fi, self.analysis)
                if hit is not None:
                    label, own = hit
                    held = cs.held - {own} if own else cs.held
                    if not held:
                        continue
                    held_s = ", ".join(
                        _short_lock(h) for h in sorted(held)
                    )
                    yield _mk(
                        BlockingCallUnderLockRule.id,
                        fi.path,
                        cs.line,
                        f"{label} while holding {held_s} in {fi.name}() "
                        "— every thread contending for the lock stalls "
                        "behind the blocking call; move it outside the "
                        "critical section",
                        [f"{fi.name} holds {held_s} ({fi.path}:{cs.line})"],
                    )
                    continue
                if mi is None:
                    continue
                callee = self.index.resolve_call(mi, cs.node.func, fi)
                if callee is None or callee.qualname == fi.qualname:
                    continue
                wit = self.blocking_witness(callee)
                if wit:
                    held_s = ", ".join(
                        _short_lock(h) for h in sorted(cs.held)
                    )
                    yield _mk(
                        BlockingCallUnderLockRule.id,
                        fi.path,
                        cs.line,
                        f"{callee.name}(...) called while holding "
                        f"{held_s} in {fi.name}() transitively blocks "
                        f"({wit[-1].strip()}) — move the blocking work "
                        "outside the critical section",
                        [
                            f"{fi.name} holds {held_s} "
                            f"({fi.path}:{cs.line})"
                        ]
                        + wit,
                    )

    # ------------------------------------------------------ check-then-act
    def cta_findings(self) -> Iterator[Finding]:
        for fi in self._functions():
            if fi.name == "__init__":
                continue
            cq = (
                f"{fi.modname}.{fi.class_name}" if fi.class_name else None
            )
            thread_aware = (
                cq in self.model.spawning_classes
                or fi.qualname in self.model.worker_paths
            )
            if not thread_aware:
                continue
            info = self.analysis.info(fi)
            for c in info.check_then_acts:
                if c.held:
                    continue
                ctxs = self.model.contexts(fi.qualname)
                trace = (
                    [f"runs on: {self._ctx_label(ctxs)}"]
                    if ctxs
                    else [f"{fi.name} ({fi.path}:{c.line})"]
                )
                yield _mk(
                    CheckThenActRaceRule.id,
                    fi.path,
                    c.line,
                    f"check-then-act on self.{c.attr} in {fi.name}() "
                    "without a lock — two threads can both see 'missing' "
                    "and both insert; hold the container's lock across "
                    "the test and the store",
                    trace,
                )


def concurrency_findings(index, contexts) -> Iterator[Finding]:
    """All project-mode concurrency findings (interproc.py hook)."""
    checker = _Checker(index, contexts)
    yield from checker.mutation_findings()
    yield from checker.order_findings()
    yield from checker.blocking_findings()
    yield from checker.cta_findings()
