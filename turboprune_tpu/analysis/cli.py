"""graftlint CLI: ``python -m turboprune_tpu.analysis [paths...]``.

Exit codes (the contract scripts/check.sh and CI build on):
  0 — analyzed clean: zero unwaived findings
  1 — at least one unwaived finding (or a failed --jaxpr-audit diff)
  2 — usage / environment error (bad path, unknown rule in --select,
      git unavailable for --changed, jax unavailable for --jaxpr-audit)

Modes:

* per-file (default) — the lexical rules over the given paths;
* ``--project`` — per-file PLUS the interprocedural layer (symbol
  table + call graph, rules fire through call chains with call-path
  traces) PLUS the config rules over every ``*.yaml`` under the paths.
  This is the pre-PR gate: ``--project turboprune_tpu conf tests``;
* ``--changed [BASE]`` — per-file rules over only the ``.py``/``.yaml``
  files changed vs ``git merge-base HEAD BASE`` (default ``main``), plus
  untracked files, so the fast half of the gate stays fast as the repo
  grows and doesn't drag in files that only changed ON main. Project
  mode intentionally has no --changed variant: call graphs and config
  cross-checks are whole-repo properties;
* ``--jaxpr-audit [ENTRY]`` — trace the real train/eval step (or a
  ``file.py:builder`` entry) under ``--dtype-policy`` and diff the
  jaxpr's convert_element_type ops against the static dtype findings
  and waivers (jaxpr_audit.py). Needs jax importable; everything else
  here runs with no accelerator stack;
* ``--sanitize [TARGET]`` — the runtime mirror of the concurrency rules
  (sanitizer.py): wrap ``threading.Lock``/``RLock``/``Condition``, drive
  the PrefetchEngine / FleetEngine load smokes (or a ``file.py:builder``
  target), fail on observed lock-order cycles and on shared-attribute
  races the static rules did not predict;
* ``--exec-manifest [emit|diff|print]`` — statically enumerate the
  compile surface (jit entries, compile sites, bucket sets, plan kinds)
  into analysis/exec_manifest.json; ``diff`` fails when the surface has
  drifted from the checked-in manifest (exec_manifest.py);
* ``--compile-audit [TARGET]`` — the runtime mirror of the manifest
  (compile_audit.py): patch jax's backend_compile, drive the serving /
  train smokes, and fail on any XLA compile the manifest does not
  explain. Needs jax, like --jaxpr-audit;
* ``--rule-docs`` — print the generated rule-catalog markdown table
  (the source of README.md's marked block).

With no paths it analyzes the installed ``turboprune_tpu`` package — the
same invocation the self-gate test makes, so "the linter passes" means the
same thing locally, in CI, and in tests/test_analysis.py.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import Optional, Sequence

from .conf_rules import CONF_RULES
from .core import RULES, analyze_files, analyze_paths, analyze_project
from .reporters import render_json, render_sarif, render_text

_EPILOG = """\
exit codes:
  0  analyzed clean: zero unwaived findings (jaxpr audit: clean diff;
     exec-manifest diff: no drift; compile audit: every compile
     attributed)
  1  at least one unwaived finding (jaxpr audit: unexplained upcast or
     unwaived static dtype finding; sanitize: observed lock-order cycle
     or a race with no static finding; exec-manifest diff: compile
     surface drifted vs the checked-in manifest; compile audit: a
     runtime XLA compile no manifest entry explains, or a compiled
     (plan, bucket) outside the declared surface)
  2  usage or environment error (bad path, unknown rule in --select,
     git unavailable for --changed, jax unavailable for
     --jaxpr-audit/--compile-audit, missing manifest)
"""


def _default_paths() -> list:
    return [str(Path(__file__).resolve().parents[1])]


def _default_project_paths() -> list:
    pkg = Path(__file__).resolve().parents[1]
    paths = [str(pkg)]
    conf = pkg.parent / "conf"
    if conf.is_dir():
        paths.append(str(conf))
    return paths


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m turboprune_tpu.analysis",
        description=(
            "graftlint: JAX-aware static analysis (host syncs in jit, "
            "retrace hazards, PRNG key reuse, rank-conditional "
            "collectives, donated-buffer reads, swallowed exceptions, "
            "dtype-flow upcast/promotion hazards; --project adds "
            "interprocedural call-chain analysis and conf/ schema "
            "cross-checking; --jaxpr-audit grounds the dtype rules in "
            "the traced jaxpr)"
        ),
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: the turboprune_tpu package)",
    )
    p.add_argument(
        "--project",
        action="store_true",
        help=(
            "whole-project mode: interprocedural jit/RNG/collective/dtype "
            "analysis over the call graph plus conf/*.yaml schema "
            "cross-checks, on top of the per-file rules"
        ),
    )
    p.add_argument(
        "--changed",
        nargs="?",
        const="main",
        metavar="BASE",
        help=(
            "lint only .py/.yaml files changed vs the merge-base of HEAD "
            "and BASE (default: main), plus untracked files"
        ),
    )
    p.add_argument(
        "--jaxpr-audit",
        nargs="?",
        const="train",
        metavar="ENTRY",
        help=(
            "trace ENTRY ('train', 'eval', 'file.py:builder' or "
            "'pkg.module:builder' returning (fn, args)) under "
            "--dtype-policy and diff jaxpr convert_element_type ops "
            "against static dtype findings and waivers (needs jax)"
        ),
    )
    p.add_argument(
        "--sanitize",
        nargs="?",
        const="all",
        metavar="TARGET",
        help=(
            "graftsan runtime concurrency sanitizer: wrap "
            "threading.Lock/RLock/Condition, drive TARGET ('pipeline', "
            "'fleet', 'all', or 'file.py:builder' returning a callable) "
            "under threaded load, fail on observed lock-order cycles and "
            "on shared-attribute races with no static "
            "unsynchronized-shared-mutation finding (a sanitizer-only "
            "race is a static blind spot)"
        ),
    )
    p.add_argument(
        "--exec-manifest",
        nargs="?",
        const="diff",
        choices=("emit", "diff", "print"),
        metavar="MODE",
        help=(
            "executable-set manifest (exec_manifest.py): statically "
            "enumerate every jit entry, compile site, bucket set and "
            "plan-signature kind; 'emit' writes "
            "analysis/exec_manifest.json, 'diff' (default) rebuilds and "
            "fails on drift vs the checked-in file, 'print' dumps the "
            "fresh manifest"
        ),
    )
    p.add_argument(
        "--compile-audit",
        nargs="?",
        const="all",
        metavar="TARGET",
        help=(
            "runtime mirror of the executable manifest "
            "(compile_audit.py): patch jax's backend_compile, drive "
            "TARGET ('serve', 'train', 'all', or 'file.py:builder' "
            "returning a callable), and fail on any XLA compile not "
            "attributed to a manifest entry/compile site, or any "
            "compiled (plan, bucket) outside the declared surface "
            "(needs jax)"
        ),
    )
    p.add_argument(
        "--rule-docs",
        action="store_true",
        help=(
            "print the README rule-catalog markdown table generated from "
            "the rule registries (the marked block in README.md must "
            "match — tests/test_analysis.py gates it)"
        ),
    )
    p.add_argument(
        "--jobs",
        type=int,
        default=0,
        metavar="N",
        help=(
            "process-pool width for --project's per-file half "
            "(0 = one per CPU, 1 = serial; finding order is identical "
            "either way)"
        ),
    )
    p.add_argument(
        "--dtype-policy",
        choices=("fp32", "bf16"),
        default="fp32",
        help=(
            "dtype policy for --jaxpr-audit's default entries: fp32 "
            "(default; must audit clean) or bf16 (casts step inputs to "
            "bfloat16 — the mixed-precision acceptance harness)"
        ),
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default=None,
        help="report format (default: text; sarif renders CI annotations)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable JSON report (alias for --format json)",
    )
    p.add_argument(
        "--show-waived",
        action="store_true",
        help="include waived findings in the text report",
    )
    p.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def _changed_python_files(base: str) -> list:
    """Lintable files changed vs the merge-base of HEAD and ``base``
    (NOT the base tip: diffing against an advanced main would drag in
    every file main changed and miss nothing-but-noise), plus untracked
    files. Py and yaml both count — per-file rules for the former, the
    schema-independent conf checks for the latter."""
    merge = subprocess.run(
        ["git", "merge-base", "HEAD", base],
        capture_output=True,
        text=True,
    )
    diff_base = (
        merge.stdout.strip()
        if merge.returncode == 0 and merge.stdout.strip()
        else base
    )
    files: list = []
    for cmd in (
        ["git", "diff", "--name-only", diff_base, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, capture_output=True, text=True, check=True
        )
        files.extend(proc.stdout.splitlines())
    out = []
    seen = set()
    for f in files:
        if (
            f.endswith((".py", ".yaml", ".yml"))
            and f not in seen
            and Path(f).exists()
        ):
            seen.add(f)
            out.append(f)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    all_rules = {**{r.id: r for r in RULES.values()}, **CONF_RULES}
    if args.list_rules:
        width = max(len(r) for r in all_rules)
        for rule in RULES.values():
            print(f"{rule.id:<{width}}  [{rule.severity}] {rule.description}")
        for rule in CONF_RULES.values():
            print(
                f"{rule.id:<{width}}  [{rule.severity}] [project] "
                f"{rule.description}"
            )
        return 0

    modes = [
        name
        for name, on in (
            ("--project", args.project),
            ("--changed", bool(args.changed)),
            ("--jaxpr-audit", bool(args.jaxpr_audit)),
            ("--sanitize", bool(args.sanitize)),
            ("--exec-manifest", bool(args.exec_manifest)),
            ("--compile-audit", bool(args.compile_audit)),
            ("--rule-docs", args.rule_docs),
        )
        if on
    ]
    if len(modes) > 1:
        print(
            f"{' and '.join(modes)} are mutually exclusive",
            file=sys.stderr,
        )
        return 2

    fmt = args.format or ("json" if args.json else "text")

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in all_rules]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(all_rules))})",
                file=sys.stderr,
            )
            return 2

    if args.jaxpr_audit:
        from .jaxpr_audit import AuditError, run_audit

        try:
            return run_audit(
                entry=args.jaxpr_audit, policy=args.dtype_policy
            )
        except AuditError as e:
            print(f"graftlint --jaxpr-audit: {e}", file=sys.stderr)
            return 2

    if args.sanitize:
        from .sanitizer import SanitizeError, run_sanitize

        try:
            return run_sanitize(args.sanitize)
        except SanitizeError as e:
            print(f"graftlint --sanitize: {e}", file=sys.stderr)
            return 2

    if args.rule_docs:
        from .reporters import render_rule_docs

        print(render_rule_docs(), end="")
        return 0

    if args.exec_manifest:
        from .exec_manifest import run_exec_manifest

        try:
            return run_exec_manifest(args.exec_manifest, paths=args.paths)
        except ValueError as e:
            print(f"graftlint --exec-manifest: {e}", file=sys.stderr)
            return 2

    if args.compile_audit:
        from .compile_audit import AuditError, run_compile_audit

        try:
            return run_compile_audit(args.compile_audit)
        except AuditError as e:
            print(f"graftlint --compile-audit: {e}", file=sys.stderr)
            return 2

    try:
        if args.changed:
            if args.paths:
                print(
                    "--changed takes no paths (it derives them from git)",
                    file=sys.stderr,
                )
                return 2
            try:
                files = _changed_python_files(args.changed)
            except (subprocess.CalledProcessError, OSError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                print(
                    f"graftlint --changed: git failed: {detail.strip()}",
                    file=sys.stderr,
                )
                return 2
            if not files:
                print(
                    f"graftlint: no lintable files changed vs {args.changed}"
                )
                return 0
            result = analyze_files(files, select=select)
        elif args.project:
            result = analyze_project(
                args.paths or _default_project_paths(),
                select=select,
                jobs=args.jobs or None,
            )
        else:
            result = analyze_paths(
                args.paths or _default_paths(), select=select
            )
    except (FileNotFoundError, OSError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if fmt == "json":
        print(render_json(result))
    elif fmt == "sarif":
        print(render_sarif(result))
    else:
        print(render_text(result, show_waived=args.show_waived))
    return 1 if result.unwaived else 0
