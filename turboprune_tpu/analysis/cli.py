"""graftlint CLI: ``python -m turboprune_tpu.analysis [paths...]``.

Exit codes (the contract scripts/check.sh and CI build on):
  0 — analyzed clean: zero unwaived findings
  1 — at least one unwaived finding
  2 — usage / environment error (bad path, unknown rule in --select,
      git unavailable for --changed)

Three modes:

* per-file (default) — the eight lexical rules over the given paths;
* ``--project`` — per-file PLUS the interprocedural layer (symbol
  table + call graph, rules fire through call chains with call-path
  traces) PLUS the config rules over every ``*.yaml`` under the paths.
  This is the pre-PR gate: ``--project turboprune_tpu conf tests``;
* ``--changed [BASE]`` — per-file rules over only the ``.py`` files
  changed vs BASE (default ``main``, via ``git diff --name-only`` plus
  untracked files), so the fast half of the gate stays fast as the repo
  grows. Project mode intentionally has no --changed variant: call
  graphs and config cross-checks are whole-repo properties.

With no paths it analyzes the installed ``turboprune_tpu`` package — the
same invocation the self-gate test makes, so "the linter passes" means the
same thing locally, in CI, and in tests/test_analysis.py.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import Optional, Sequence

from .conf_rules import CONF_RULES
from .core import RULES, analyze_paths, analyze_project
from .reporters import render_json, render_text


def _default_paths() -> list:
    return [str(Path(__file__).resolve().parents[1])]


def _default_project_paths() -> list:
    pkg = Path(__file__).resolve().parents[1]
    paths = [str(pkg)]
    conf = pkg.parent / "conf"
    if conf.is_dir():
        paths.append(str(conf))
    return paths


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m turboprune_tpu.analysis",
        description=(
            "graftlint: JAX-aware static analysis (host syncs in jit, "
            "retrace hazards, PRNG key reuse, rank-conditional "
            "collectives, donated-buffer reads, swallowed exceptions; "
            "--project adds interprocedural call-chain analysis and "
            "conf/ schema cross-checking)"
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: the turboprune_tpu package)",
    )
    p.add_argument(
        "--project",
        action="store_true",
        help=(
            "whole-project mode: interprocedural jit/RNG/collective "
            "analysis over the call graph plus conf/*.yaml schema "
            "cross-checks, on top of the per-file rules"
        ),
    )
    p.add_argument(
        "--changed",
        nargs="?",
        const="main",
        metavar="BASE",
        help=(
            "lint only .py files changed vs BASE (default: main) per "
            "git diff --name-only, plus untracked files"
        ),
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable JSON report"
    )
    p.add_argument(
        "--show-waived",
        action="store_true",
        help="include waived findings in the text report",
    )
    p.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def _changed_python_files(base: str) -> list:
    """Changed-vs-base plus untracked .py files, as git reports them."""
    files: list = []
    for cmd in (
        ["git", "diff", "--name-only", base, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, capture_output=True, text=True, check=True
        )
        files.extend(proc.stdout.splitlines())
    out = []
    seen = set()
    for f in files:
        if f.endswith(".py") and f not in seen and Path(f).exists():
            seen.add(f)
            out.append(f)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    all_rules = {**{r.id: r for r in RULES.values()}, **CONF_RULES}
    if args.list_rules:
        width = max(len(r) for r in all_rules)
        for rule in RULES.values():
            print(f"{rule.id:<{width}}  [{rule.severity}] {rule.description}")
        for rule in CONF_RULES.values():
            print(
                f"{rule.id:<{width}}  [{rule.severity}] [project] "
                f"{rule.description}"
            )
        return 0

    if args.project and args.changed:
        print(
            "--project and --changed are mutually exclusive (the project "
            "layer is a whole-repo property)",
            file=sys.stderr,
        )
        return 2

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in all_rules]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(all_rules))})",
                file=sys.stderr,
            )
            return 2

    try:
        if args.changed:
            if args.paths:
                print(
                    "--changed takes no paths (it derives them from git)",
                    file=sys.stderr,
                )
                return 2
            try:
                files = _changed_python_files(args.changed)
            except (subprocess.CalledProcessError, OSError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                print(
                    f"graftlint --changed: git failed: {detail.strip()}",
                    file=sys.stderr,
                )
                return 2
            if not files:
                print(
                    f"graftlint: no .py files changed vs {args.changed}"
                )
                return 0
            result = analyze_paths(files, select=select)
        elif args.project:
            result = analyze_project(
                args.paths or _default_project_paths(), select=select
            )
        else:
            result = analyze_paths(
                args.paths or _default_paths(), select=select
            )
    except (FileNotFoundError, OSError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(render_json(result))
    else:
        print(render_text(result, show_waived=args.show_waived))
    return 1 if result.unwaived else 0
