"""graftlint CLI: ``python -m turboprune_tpu.analysis [paths...]``.

Exit codes (the contract scripts/check.sh and CI build on):
  0 — analyzed clean: zero unwaived findings
  1 — at least one unwaived finding
  2 — usage / environment error (bad path, unknown rule in --select)

With no paths it analyzes the installed ``turboprune_tpu`` package — the
same invocation the self-gate test makes, so "the linter passes" means the
same thing locally, in CI, and in tests/test_analysis.py.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional, Sequence

from .core import RULES, analyze_paths
from .reporters import render_json, render_text


def _default_paths() -> list:
    return [str(Path(__file__).resolve().parents[1])]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m turboprune_tpu.analysis",
        description=(
            "graftlint: JAX-aware static analysis (host syncs in jit, "
            "retrace hazards, PRNG key reuse, rank-conditional "
            "collectives, donated-buffer reads, swallowed exceptions)"
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: the turboprune_tpu package)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable JSON report"
    )
    p.add_argument(
        "--show-waived",
        action="store_true",
        help="include waived findings in the text report",
    )
    p.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in RULES)
        for rule in RULES.values():
            print(f"{rule.id:<{width}}  [{rule.severity}] {rule.description}")
        return 0

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in RULES]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULES))})",
                file=sys.stderr,
            )
            return 2

    try:
        result = analyze_paths(args.paths or _default_paths(), select=select)
    except (FileNotFoundError, OSError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(render_json(result))
    else:
        print(render_text(result, show_waived=args.show_waived))
    return 1 if result.unwaived else 0
