"""Experiment management: directories, seeds, config snapshots, metrics.

Rebuilds the reference's harness_utils
(/root/reference/utils/harness_utils.py): ``gen_expt_dir`` (config-encoding
prefix + uuid/timestamp, fixed subdir layout, :49-94), ``set_seed`` (:97-114),
``save_config`` (:148-156), the pandas CSV metric channels
(standard_pruning_harness.py:243-269), the rich console panels
(harness_utils.py:248-351), and a ``resume_experiment`` that actually works
(the reference's is called with the wrong arity, run_experiment.py:61 —
SURVEY.md §5 "Failure detection").
"""

from __future__ import annotations

import dataclasses
import os
import random
import uuid
from datetime import datetime
from pathlib import Path
from typing import Optional

import numpy as np
import pandas as pd
import yaml

from ..config.schema import MainConfig, config_to_dict

SUBDIRS = ("checkpoints", "metrics", "metrics/level_wise_metrics", "artifacts")


def expt_prefix(cfg: MainConfig) -> str:
    """Config-encoding experiment name (reference builds the same kind of
    stub from dataset/model/prune knobs, harness_utils.py:64-82)."""
    pp = cfg.pruning_params
    parts = [
        cfg.dataset_params.dataset_name.lower(),
        cfg.model_params.model_name,
        pp.prune_method.replace(" ", "_"),
        pp.training_type,
        f"sp{pp.target_sparsity:g}",
        f"seed{cfg.experiment_params.seed}",
    ]
    if cfg.cyclic_training.num_cycles > 1:
        parts.append(f"cyc{cfg.cyclic_training.num_cycles}")
    return "_".join(parts)


def gen_expt_dir(cfg: MainConfig) -> tuple[str, str]:
    """(prefix, expt_dir); creates the fixed subdir layout
    (harness_utils.py:87-94)."""
    prefix = expt_prefix(cfg)
    stamp = datetime.now().strftime("%Y%m%d_%H%M%S")
    unique = f"{prefix}__{stamp}_{uuid.uuid4().hex[:8]}"
    expt_dir = Path(cfg.experiment_params.base_dir) / unique
    for sub in SUBDIRS:
        (expt_dir / sub).mkdir(parents=True, exist_ok=True)
    return prefix, str(expt_dir)


def resume_experiment(cfg: MainConfig) -> tuple[str, str, int]:
    """(prefix, expt_dir, resume_level) for an existing experiment dir.

    Requires ``experiment_params.resume_experiment_stuff`` with the dir name
    under base_dir. Returns the level to CONTINUE FROM (training resumes at
    ``resume_level``, consuming ``model_level_{resume_level-1}``) — the
    reference intended exactly this but the code path was unreachable
    (harness_utils.py:368-386)."""
    stuff = cfg.experiment_params.resume_experiment_stuff
    if stuff is None or not stuff.resume_expt_name:
        raise ValueError(
            "resume_experiment=true requires "
            "experiment_params.resume_experiment_stuff.resume_expt_name"
        )
    expt_dir = Path(cfg.experiment_params.base_dir) / stuff.resume_expt_name
    if not expt_dir.exists():
        raise FileNotFoundError(f"cannot resume: {expt_dir} does not exist")
    for sub in SUBDIRS:
        (expt_dir / sub).mkdir(parents=True, exist_ok=True)
    prefix = stuff.resume_expt_name.split("__")[0]
    return prefix, str(expt_dir), stuff.resume_level


def set_seed(seed: int, deterministic: bool = False) -> None:
    """Host-side seeding (reference set_seed, harness_utils.py:97-114).
    Device-side randomness is explicit-key JAX PRNG and needs no global
    seeding; this covers numpy/python used by data pipelines."""
    os.environ["PYTHONHASHSEED"] = str(seed)
    random.seed(seed)
    np.random.seed(seed)
    del deterministic  # XLA is deterministic-by-default for our op set


def config_fingerprint(cfg: MainConfig) -> str:
    """Short content hash of the TRAINING-RELEVANT config, used to stamp the
    mid-level checkpoint slot: a resume whose config diverged (lr, epoch
    budget, loader type, ...) must not silently restore mid-trajectory state
    trained under the old config.

    Excluded from the hash: the resume knobs themselves (a resumed run
    flips ``resume_experiment`` and MUST still match its own slot) and the
    serve group (serving knobs don't touch training)."""
    import hashlib
    import json

    d = config_to_dict(cfg)
    ep = d.get("experiment_params") or {}
    ep.pop("resume_experiment", None)
    ep.pop("resume_experiment_stuff", None)
    d.pop("serve", None)
    blob = json.dumps(d, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def save_config(expt_dir: str, cfg: MainConfig) -> Path:
    """Snapshot the composed config (reference save_config,
    harness_utils.py:148-156)."""
    out = Path(expt_dir) / "expt_config.yaml"
    with open(out, "w") as f:
        yaml.safe_dump(config_to_dict(cfg), f, sort_keys=False)
    return out


class MetricsLogger:
    """The reference's CSV metric channels (standard_pruning_harness.py:
    243-269): per-level ``metrics/level_wise_metrics/level_{L}_metrics.csv``
    rows of epoch/train/test stats, plus an append-mode
    ``metrics/{prefix}_summary.csv`` with one row per level."""

    def __init__(self, expt_dir: str, prefix: str):
        self.expt_dir = Path(expt_dir)
        self.prefix = prefix
        self.level_rows: list[dict] = []

    def log_epoch(self, row: dict) -> None:
        self.level_rows.append(dict(row))

    def finish_level(self, level: int, summary_extra: Optional[dict] = None) -> dict:
        """Write the level CSV, append the summary row, reset the buffer.
        File writes are host-0-only (the reference's rank-0 logging rule,
        standard_pruning_harness.py:243); every host still gets the summary
        dict back."""
        import jax

        df = pd.DataFrame(self.level_rows)
        summary = {}
        if len(df):
            last = df.iloc[-1].to_dict()
            summary.update(last)
            if "test_acc" in df:
                summary["max_test_acc"] = float(df["test_acc"].max())
        # After the row merge: pandas floatifies ints (level 0 -> 0.0).
        summary["level"] = level
        summary.update(summary_extra or {})

        if jax.process_index() == 0:
            level_dir = self.expt_dir / "metrics" / "level_wise_metrics"
            level_dir.mkdir(parents=True, exist_ok=True)
            df.to_csv(level_dir / f"level_{level}_metrics.csv", index=False)
            summary_path = self.expt_dir / "metrics" / f"{self.prefix}_summary.csv"
            pd.DataFrame([summary]).to_csv(
                summary_path,
                mode="a",
                header=not summary_path.exists(),
                index=False,
            )
        self.level_rows = []
        return summary


def display_training_info(cfg: MainConfig, level: int, density: float) -> None:
    """Rich config/level panels (reference display_training_info,
    harness_utils.py:248-351); degrades to prints when rich is absent."""
    try:
        from rich.console import Console
        from rich.panel import Panel
        from rich.table import Table
    except ImportError:
        # Only a MISSING rich degrades to the plain print — a render error
        # with rich present propagates (it would mean the config itself is
        # broken, which must not be swallowed).
        print(f"[level {level}] density={density:.4f}")
        return

    console = Console()
    t = Table(title=f"Level {level} — density {density:.4f}")
    t.add_column("knob")
    t.add_column("value")
    for section in (
        "dataset_params",
        "model_params",
        "pruning_params",
        "optimizer_params",
    ):
        sub = getattr(cfg, section)
        for f in dataclasses.fields(sub):
            t.add_row(f"{section}.{f.name}", str(getattr(sub, f.name)))
    console.print(Panel(t, border_style="cyan", expand=False))
