"""Experiment management + checkpointing (reference layer:
/root/reference/utils/harness_utils.py + torch.save plumbing)."""

from .checkpoint import (
    MID_LEVEL,
    MODEL_INIT,
    MODEL_REWIND,
    OPTIMIZER_INIT,
    OPTIMIZER_REWIND,
    ExperimentCheckpoints,
    pack_mask_tree,
    reset_weights,
    restore_model_tree,
    restore_pytree,
    save_model_tree,
    save_pytree,
    unpack_mask_tree,
)
from .experiment import (
    MetricsLogger,
    config_fingerprint,
    display_training_info,
    expt_prefix,
    gen_expt_dir,
    resume_experiment,
    save_config,
    set_seed,
)

__all__ = [
    "ExperimentCheckpoints",
    "reset_weights",
    "save_pytree",
    "restore_pytree",
    "save_model_tree",
    "restore_model_tree",
    "pack_mask_tree",
    "unpack_mask_tree",
    "MID_LEVEL",
    "MODEL_INIT",
    "MODEL_REWIND",
    "OPTIMIZER_INIT",
    "OPTIMIZER_REWIND",
    "MetricsLogger",
    "config_fingerprint",
    "gen_expt_dir",
    "resume_experiment",
    "expt_prefix",
    "save_config",
    "set_seed",
    "display_training_info",
]
