"""Orbax checkpoints with the reference's artifact roles.

The reference persists five artifact roles with torch.save
(/root/reference/run_experiment.py:82-123,
standard_pruning_harness.py:190-223, harness_utils.py:354-365):

  checkpoints/model_init          level-0 starting weights (imp rewind target)
  checkpoints/model_rewind        weights at rewind_epoch of level 0 (wr target)
  artifacts/optimizer_init        optimizer state at level 0 start
  artifacts/optimizer_rewind      optimizer state at rewind_epoch
  checkpoints/model_level_{L}     end-of-level weights (next level's input)

Here a "model" checkpoint is the ``{params, masks, batch_stats}`` pytree
(the reference's state_dict carries mask buffers and BN running stats the
same way) and an "optimizer" checkpoint is the optax ``opt_state`` pytree.
Rewind semantics (reference PruneModel.reset_weights,
custom_models.py:112-146): imp -> restore params+batch_stats from init,
wr -> from rewind, lrr / at_init -> keep trained weights; masks are NEVER
restored — the freshly pruned masks always survive a rewind.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

PyTree = Any

MODEL_INIT = "model_init"
MODEL_REWIND = "model_rewind"
OPTIMIZER_INIT = "optimizer_init"
OPTIMIZER_REWIND = "optimizer_rewind"
MID_LEVEL = "mid_level"

_LEVEL_RE = re.compile(r"^model_level_(\d+)$")


def _primary_only_checkpointer() -> ocp.StandardCheckpointer:
    """A Checkpointer whose internal barriers involve ONLY process 0.

    ocp.StandardCheckpointer.save() unconditionally runs
    sync_global_processes barriers across every process in the world — so a
    save called under ``if is_primary()`` would leave host 0 stuck in
    Orbax's barrier while the other hosts wait at our own sync_hosts().
    MultiprocessingOptions(active_processes={0}) tells Orbax only process 0
    participates, making primary-only save safe."""
    if jax.process_count() == 1:
        return ocp.StandardCheckpointer()
    return ocp.StandardCheckpointer(
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=0,
            active_processes={0},
            barrier_sync_key_prefix="tpk_primary_save",
        )
    )


def save_pytree(path: str | Path, tree: PyTree) -> None:
    """Atomic directory-style save (overwrites an existing checkpoint).

    Multi-host: PRIMARY-ONLY. Framework state is replicated across hosts
    (params/masks/opt_state all live on every host — see parallel/mesh.py
    ``replicated``), so host 0 materializes the tree as numpy and writes
    alone; everyone else waits at a barrier. N hosts doing rmtree+save on a
    shared filesystem would stomp one directory (the reference's torch.save
    is likewise rank-0-only, standard_pruning_harness.py:190-199).

    REQUIREMENT: on >1 process the experiment dir must be on storage every
    host can read (NFS/GCS/localhost-shared disk) — restore_pytree is called
    by ALL hosts (reset_weights / optimizer rewind / level resume)."""
    from ..parallel.multihost import is_primary, sync_hosts

    path = Path(path).resolve()
    if is_primary():
        # device_get works per-host on replicated arrays; saving numpy keeps
        # the array leaves fully addressable for the single-process save.
        host_tree = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, jax.Array)
            else x,
            tree,
        )
        ckptr = _primary_only_checkpointer()
        if path.exists():
            import shutil

            shutil.rmtree(path)
        ckptr.save(path, host_tree)
        ckptr.wait_until_finished()
    sync_hosts(f"save_pytree:{path.name}")


def restore_pytree(path: str | Path, like: Optional[PyTree] = None) -> PyTree:
    """Restore; pass ``like`` (a matching concrete/abstract pytree) to get
    exact container types back (optax namedtuples, custom nodes)."""
    path = Path(path).resolve()
    ckptr = ocp.StandardCheckpointer()
    if like is None:
        return ckptr.restore(path)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
    return ckptr.restore(path, abstract)


# --- bit-packed mask payloads --------------------------------------------
# Boolean mask trees serialize 1 byte/element; at ResNet50 scale that is
# ~25 MB of masks PER checkpoint role, all of it bits. Masks are packed to
# uint8 bitfields (np.packbits — host-side; the save path materializes
# numpy anyway) with an explicit shape vector per leaf, an 8x smaller
# payload. Checkpoints written before this change carry raw bool masks;
# ``restore_model_tree`` detects which layout is on disk from Orbax's
# _METADATA manifest and reads either, so legacy experiment dirs stay
# loadable.

MASKS_KEY = "masks"
MASKS_PACKED_KEY = "masks_packed"


def _is_none(x) -> bool:
    return x is None


def pack_mask_tree(masks: PyTree) -> PyTree:
    """bool leaves -> {"bits": uint8[ceil(n/8)], "shape": int64[ndim]};
    None leaves (non-prunable positions) pass through."""

    def pack(m):
        if m is None:
            return None
        arr = np.asarray(jax.device_get(m)).astype(bool)
        return {
            "bits": np.packbits(arr.reshape(-1)),
            "shape": np.asarray(arr.shape, np.int64),
        }

    return jax.tree.map(pack, masks, is_leaf=_is_none)


def unpack_mask_tree(packed: PyTree) -> PyTree:
    """Inverse of pack_mask_tree; shapes come from the stored metadata."""

    def unpack(leaf):
        if leaf is None:
            return None
        shape = tuple(int(s) for s in np.asarray(leaf["shape"]))
        n = int(np.prod(shape)) if shape else 1
        bits = np.unpackbits(np.asarray(leaf["bits"]), count=n)
        return bits.astype(bool).reshape(shape)

    def is_packed_leaf(x):
        return x is None or (isinstance(x, dict) and set(x) == {"bits", "shape"})

    return jax.tree.map(unpack, packed, is_leaf=is_packed_leaf)


def packed_mask_like(masks_like: PyTree) -> PyTree:
    """Abstract packed tree (for restore-with-like) from an unpacked
    mask-tree template — shapes are derivable: prod(shape) bits."""

    def like(m):
        if m is None:
            return None
        n = int(np.prod(m.shape)) if m.shape else 1
        return {
            "bits": np.zeros((n + 7) // 8, np.uint8),
            "shape": np.zeros(len(m.shape), np.int64),
        }

    return jax.tree.map(like, masks_like, is_leaf=_is_none)


def _has_packed_masks(path: Path) -> bool:
    """Did this checkpoint serialize masks bit-packed? Read from Orbax's
    _METADATA manifest (tree_metadata keys are stringified key-paths);
    unreadable/absent manifest -> assume the legacy raw-bool layout."""
    try:
        meta = json.loads((Path(path) / "_METADATA").read_text())
    except (OSError, ValueError):
        return False
    keys = meta.get("tree_metadata", {})
    return any(f"'{MASKS_PACKED_KEY}'" in k for k in keys)


def save_model_tree(path: str | Path, tree: dict) -> None:
    """Save a model-role tree ({"params", "masks", ...extras}) with the
    mask payload bit-packed under ``masks_packed``."""
    out = dict(tree)
    out[MASKS_PACKED_KEY] = pack_mask_tree(out.pop(MASKS_KEY))
    save_pytree(path, out)


def restore_model_tree(path: str | Path, like: dict) -> dict:
    """Restore a model-role tree against an UNPACKED ``like`` (with a
    "masks" entry), transparently handling both layouts: bit-packed
    (current) and raw bool (legacy checkpoints from before the packing
    change). Returns the unpacked form either way."""
    if not _has_packed_masks(Path(path).resolve()):
        return restore_pytree(path, like)
    plike = dict(like)
    plike[MASKS_PACKED_KEY] = packed_mask_like(plike.pop(MASKS_KEY))
    restored = restore_pytree(path, plike)
    restored[MASKS_KEY] = unpack_mask_tree(restored.pop(MASKS_PACKED_KEY))
    return restored


class ExperimentCheckpoints:
    """Role-addressed checkpoints under an experiment directory (the
    reference's checkpoints/ + artifacts/ split, harness_utils.py:90-93)."""

    def __init__(self, expt_dir: str | Path):
        self.expt_dir = Path(expt_dir)
        self.checkpoints_dir = self.expt_dir / "checkpoints"
        self.artifacts_dir = self.expt_dir / "artifacts"
        self.checkpoints_dir.mkdir(parents=True, exist_ok=True)
        self.artifacts_dir.mkdir(parents=True, exist_ok=True)

    # --- path helpers -----------------------------------------------------
    def model_path(self, role: str) -> Path:
        return self.checkpoints_dir / role

    def optimizer_path(self, role: str) -> Path:
        return self.artifacts_dir / role

    def level_path(self, level: int) -> Path:
        return self.checkpoints_dir / f"model_level_{level}"

    # --- model roles ------------------------------------------------------
    def model_state(self, state) -> dict:
        return {
            "params": state.params,
            "masks": state.masks,
            "batch_stats": state.batch_stats,
        }

    def save_model(self, role: str, state) -> None:
        save_model_tree(self.model_path(role), self.model_state(state))

    def load_model(self, role: str, like_state) -> dict:
        return restore_model_tree(
            self.model_path(role), self.model_state(like_state)
        )

    def save_level(self, level: int, state) -> None:
        save_model_tree(self.level_path(level), self.model_state(state))

    def load_level(self, level: int, like_state) -> dict:
        return restore_model_tree(
            self.level_path(level), self.model_state(like_state)
        )

    def has_model(self, role: str) -> bool:
        return self.model_path(role).exists()

    def has_level(self, level: int) -> bool:
        return self.level_path(level).exists()

    def saved_levels(self) -> list[int]:
        out = []
        for p in self.checkpoints_dir.iterdir():
            m = _LEVEL_RE.match(p.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # --- mid-level (epoch-granular) role ----------------------------------
    # Beyond-reference: the reference can only resume at level granularity
    # (a preemption at epoch 85/90 replays the whole level). On preemptible
    # TPUs epoch-granular re-entry is the robustness feature that actually
    # matters (SURVEY.md §5), so one rotating slot holds the FULL train
    # state (params/masks/batch_stats/opt_state/step) plus a tiny JSON
    # header that can be peeked without deserializing the state.

    def mid_level_path(self) -> Path:
        return self.checkpoints_dir / MID_LEVEL

    def _mid_level_meta_path(self) -> Path:
        return self.checkpoints_dir / "mid_level_meta.json"

    def save_mid_level(self, level: int, epoch: int, state, meta: dict) -> None:
        import json

        from ..parallel.multihost import is_primary, sync_hosts

        # The (level, epoch) tag is stored in BOTH the (atomically-written)
        # Orbax tree and the JSON header. A preemption between the two
        # writes leaves them disagreeing; load_mid_level detects that and
        # the harness falls back to replaying the level — never a mixed
        # old-header/new-state restore.
        tag = level * 1_000_000 + epoch  # int: Orbax round-trips it exactly
        save_model_tree(
            self.mid_level_path(),
            {
                "params": state.params,
                "masks": state.masks,
                "batch_stats": state.batch_stats,
                "opt_state": state.opt_state,
                "step": state.step,
                "tag": tag,
            },
        )
        if is_primary():
            p = self._mid_level_meta_path()
            tmp = p.with_suffix(".tmp")  # atomic: no truncated JSON on crash
            tmp.write_text(json.dumps({"level": level, "epoch": epoch, **meta}))
            tmp.replace(p)
        sync_hosts("mid_level_meta")

    def peek_mid_level(self) -> Optional[dict]:
        """Header {level, epoch, ...} or None — no state deserialization.
        The header may be one save older than the state tree (see
        save_mid_level); load_mid_level is the consistency authority."""
        import json

        p = self._mid_level_meta_path()
        if not p.exists() or not self.mid_level_path().exists():
            return None
        try:
            return json.loads(p.read_text())
        except (ValueError, OSError):
            return None

    def load_mid_level(self, like_state, expect_level: int, expect_epoch: int):
        """Restore the slot; returns the state dict, or None when the slot's
        embedded tag disagrees with the header-derived expectation (a torn
        save — the caller must replay the level from its start)."""
        restored = restore_model_tree(
            self.mid_level_path(),
            {
                "params": like_state.params,
                "masks": like_state.masks,
                "batch_stats": like_state.batch_stats,
                "opt_state": like_state.opt_state,
                "step": like_state.step,
                "tag": 0,
            },
        )
        if int(restored.pop("tag")) != expect_level * 1_000_000 + expect_epoch:
            return None
        return restored

    # Stream-position loaders (grain): each host's iterator state is ITS
    # OWN shard position, so blobs are per-host files (unique paths — no
    # cross-host write conflict, unlike the shared JSON header which is
    # primary-only and would silently hand every host the primary's
    # position). An 8-byte (level, epoch) tag prefixes the blob so a
    # preemption between the state save and the stream write cannot pair a
    # stale stream with a newer state — the loader falls back to a fresh
    # pass instead.

    def _mid_level_stream_path(self, pid: int) -> Path:
        return self.checkpoints_dir / f"mid_level_stream_{pid}"

    def save_mid_level_stream(
        self, level: int, epoch: int, blob: bytes, pid: int
    ) -> None:
        tag = (level * 1_000_000 + epoch).to_bytes(8, "big")
        p = self._mid_level_stream_path(pid)
        tmp = p.with_suffix(".tmp")
        tmp.write_bytes(tag + blob)
        tmp.replace(p)

    def load_mid_level_stream(
        self, level: int, epoch: int, pid: int
    ) -> Optional[bytes]:
        """The blob, or None when absent / tagged for a different save."""
        p = self._mid_level_stream_path(pid)
        if not p.exists():
            return None
        raw = p.read_bytes()
        if len(raw) < 8 or int.from_bytes(raw[:8], "big") != (
            level * 1_000_000 + epoch
        ):
            return None
        return raw[8:]

    def clear_mid_level(self) -> None:
        """Drop the slot (primary-only). Called whenever training reaches a
        level the slot does not belong to: levels run in ascending order, so
        a non-matching slot is always from an abandoned trajectory and would
        otherwise hijack a later re-run of its level (e.g. resume at level 2
        after a preemption at level 3 — the recomputed level-3 entry must
        not restore the old trajectory's state)."""
        import shutil

        from ..parallel.multihost import is_primary, sync_hosts

        if is_primary():
            self._mid_level_meta_path().unlink(missing_ok=True)
            if self.mid_level_path().exists():
                shutil.rmtree(self.mid_level_path())
            for p in self.checkpoints_dir.glob("mid_level_stream_*"):
                p.unlink(missing_ok=True)
        sync_hosts("mid_level_clear")

    # --- optimizer roles --------------------------------------------------
    def save_optimizer(self, role: str, opt_state) -> None:
        save_pytree(self.optimizer_path(role), opt_state)

    def load_optimizer(self, role: str, like_opt_state):
        return restore_pytree(self.optimizer_path(role), like_opt_state)


def reset_weights(training_type: str, state, ckpts: ExperimentCheckpoints):
    """Post-prune rewind (reference reset_weights semantics,
    custom_models.py:112-146): restores params + batch_stats from the role'd
    checkpoint, KEEPS the current (just-pruned) masks.

      imp      -> model_init
      wr       -> model_rewind
      lrr      -> no-op (learning-rate rewinding keeps trained weights)
      at_init  -> no-op (PaI never rewinds)
    """
    role = {"imp": MODEL_INIT, "wr": MODEL_REWIND}.get(training_type)
    if role is None:
        return state
    restored = ckpts.load_model(role, state)
    return state.replace(
        params=restored["params"], batch_stats=restored["batch_stats"]
    )
