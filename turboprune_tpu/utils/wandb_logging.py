"""Optional wandb channel (reference: rank-0 wandb.init + per-epoch metric
logging, /root/reference/run_experiment.py:57-59,
standard_pruning_harness.py:271-275). Degrades to a no-op when wandb is not
installed or ``experiment_params.use_wandb`` is false — the environment this
framework targets is often egress-free. The reference's per-STEP lr logging
(base_harness.py:129-130) is deliberately dropped: it forces a host sync
every step and the lr is a pure function of the step count anyway."""

from __future__ import annotations

from typing import Optional


class WandbRun:
    """No-op unless wandb imports AND use_wandb is set."""

    def __init__(self, cfg, prefix: str, expt_dir: str):
        import jax

        self._run = None
        if not cfg.experiment_params.use_wandb or jax.process_index() != 0:
            return
        try:
            import wandb

            from ..config.schema import config_to_dict

            self._run = wandb.init(
                project=cfg.experiment_params.wandb_project_name,
                name=prefix,
                config=config_to_dict(cfg),
                dir=expt_dir,
            )
        except Exception as e:  # pragma: no cover
            print(f"[wandb] disabled ({e})", flush=True)

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        if self._run is not None:
            self._run.log(metrics, step=step)

    def finish(self) -> None:
        if self._run is not None:
            self._run.finish()
            self._run = None
