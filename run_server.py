#!/usr/bin/env python
"""Serve pruned TurboPrune-TPU checkpoints over HTTP.

Usage:
    python run_server.py --expt-dir experiments/<dir> [serve.port=8080 ...]
    python run_server.py serve.expt_dir=experiments/<dir> serve.checkpoint_level=3
    python run_server.py --config-name serve serve=fleet \
        "serve.fleet.expt_dirs=[experiments/<dir>]"   # every level, one process

The serve group composes Hydra-style from conf/serve/ (see conf/serve.yaml);
the model architecture and input geometry come from the experiment dir's own
expt_config.yaml snapshot, so the served checkpoint always matches its model.

Endpoints:
    POST /predict   {"instances": [[H][W][C] floats, ...], "model": "level_3"}
                    ("model" routes within a fleet; omit for the default)
    GET  /healthz   checkpoint level/density, buckets, queue depth
                    (fleet: one row per registered model)
    GET  /metrics   Prometheus text (latency histogram, throughput,
                    queue depth, compile/AOT-cache hit/miss; fleet series
                    are labelled by model id)

SIGTERM triggers a graceful shutdown: the listener stops, already-accepted
requests are answered for up to serve.drain_timeout_s, then the process
exits — a rolling restart drops nothing it had accepted.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def parse_args(argv):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--config-name",
        default="serve",
        help="top-level config under conf/ (default: serve)",
    )
    parser.add_argument(
        "--config-path", default=None, help="alternate config root directory"
    )
    parser.add_argument(
        "--expt-dir",
        default="",
        help="experiment directory to serve (overrides serve.expt_dir)",
    )
    parser.add_argument(
        "overrides",
        nargs="*",
        help="dotted overrides like serve.port=8080 serve.max_batch=64",
    )
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])

    from turboprune_tpu.config.compose import compose
    from turboprune_tpu.serve import build_server

    cfg = compose(args.config_name, args.overrides, args.config_path)
    server = build_server(cfg, expt_dir=args.expt_dir)
    host, port = server.server_address[:2]
    if server.fleet is not None:
        info = server.fleet.info()
        models = ", ".join(sorted(info["models"]))
        print(
            f"serving fleet of {len(info['models'])} models "
            f"(default={info['default_model']}, "
            f"resident<={info['max_resident_models']})\n"
            f"  models: {models}\n"
            f"  POST http://{host}:{port}/predict "
            f'{{"instances": ..., "model": "<id>"}}   '
            f"GET /healthz   GET /metrics",
            flush=True,
        )
    else:
        info = server.engine.info()
        print(
            f"serving {info['source']}\n"
            f"  level={info['level']} density={info['density']} "
            f"buckets={info['buckets']} "
            f"compiled={info['compiled_buckets']}\n"
            f"  POST http://{host}:{port}/predict   "
            f"GET /healthz   GET /metrics",
            flush=True,
        )

    def _on_sigterm(signum, frame):
        # shutdown() handshakes with the serve_forever loop running on THIS
        # (main) thread — calling it inline here would deadlock, so the
        # drain runs on its own thread while serve_forever unwinds below.
        print("\nSIGTERM: draining in-flight requests", flush=True)
        threading.Thread(
            target=server.graceful_shutdown,
            name="turboprune-drain",
            daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("\nshutting down", flush=True)
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
