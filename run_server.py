#!/usr/bin/env python
"""Serve a pruned TurboPrune-TPU checkpoint over HTTP.

Usage:
    python run_server.py --expt-dir experiments/<dir> [serve.port=8080 ...]
    python run_server.py serve.expt_dir=experiments/<dir> serve.checkpoint_level=3

The serve group composes Hydra-style from conf/serve/ (see conf/serve.yaml);
the model architecture and input geometry come from the experiment dir's own
expt_config.yaml snapshot, so the served checkpoint always matches its model.

Endpoints:
    POST /predict   {"instances": [[H][W][C] floats, ...]}
    GET  /healthz   checkpoint level/density, buckets, queue depth
    GET  /metrics   Prometheus text (latency histogram, throughput,
                    queue depth, compile-cache hit/miss)
"""

from __future__ import annotations

import argparse
import sys


def parse_args(argv):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--config-name",
        default="serve",
        help="top-level config under conf/ (default: serve)",
    )
    parser.add_argument(
        "--config-path", default=None, help="alternate config root directory"
    )
    parser.add_argument(
        "--expt-dir",
        default="",
        help="experiment directory to serve (overrides serve.expt_dir)",
    )
    parser.add_argument(
        "overrides",
        nargs="*",
        help="dotted overrides like serve.port=8080 serve.max_batch=64",
    )
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])

    from turboprune_tpu.config.compose import compose
    from turboprune_tpu.serve import build_server

    cfg = compose(args.config_name, args.overrides, args.config_path)
    server = build_server(cfg, expt_dir=args.expt_dir)
    info = server.engine.info()
    host, port = server.server_address[:2]
    print(
        f"serving {info['source']}\n"
        f"  level={info['level']} density={info['density']} "
        f"buckets={info['buckets']} "
        f"compiled={info['compiled_buckets']}\n"
        f"  POST http://{host}:{port}/predict   "
        f"GET /healthz   GET /metrics",
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("\nshutting down", flush=True)
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
