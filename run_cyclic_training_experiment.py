#!/usr/bin/env python
"""Cyclic-training pruning experiment CLI (reference:
/root/reference/run_cyclic_training_experiment.py).

Same outer structure as run_experiment.py but trains each sparsity level in
``cyclic_training.num_cycles`` cycles with the LR schedule re-warmed each
cycle (strategy knob splits the epoch budget — 8 strategies,
turboprune_tpu/pruning/densities.py:generate_cyclical_schedule).
"""

from __future__ import annotations

import sys

from run_experiment import parse_args


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])

    from turboprune_tpu.config.compose import compose
    from turboprune_tpu.driver import run_cyclic
    from turboprune_tpu.parallel import initialize_distributed, is_primary

    cfg = compose(args.config_name, args.overrides, args.config_path)
    initialize_distributed()
    expt_dir, summaries = run_cyclic(cfg)
    if is_primary():
        print(f"\nCyclic experiment complete: {expt_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
